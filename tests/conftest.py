"""Test configuration: force an 8-device virtual CPU mesh before jax initializes.

The reference has no test suite (SURVEY.md §4); this build creates one. Multi-device
sharding paths are exercised on a virtual CPU mesh per jax's
xla_force_host_platform_device_count escape hatch, so no TPU is needed to run tests.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import tempfile  # noqa: E402

# persistent XLA compile cache for the whole suite (runtime/compileobs.py):
# the fault-injection / supervisor / multihost tests spawn subprocess
# children that would each cold-compile the identical tiny-grid programs;
# with the cache they warm-start from disk, keeping tier-1 inside its wall
# budget. The env var propagates to every child (their engines enable it in
# their constructors); content-addressed keys make it correctness-neutral.
os.environ.setdefault(
    "REDCLIFF_COMPILE_CACHE",
    os.path.join(tempfile.gettempdir(), "redcliff_t1_xla_cache"))

import jax  # noqa: E402

# hard override via config (not env): the session sitecustomize registers the
# axon TPU backend and wins over JAX_PLATFORMS env; tests must run on the
# virtual CPU mesh for determinism and f32 matmul exactness
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

from redcliff_tpu.runtime import compileobs  # noqa: E402

compileobs.enable_cache()

import time  # noqa: E402

# ---------------------------------------------------------------------------
# tier-1 wall-clock guard: the CI command wraps the suite in
# `timeout -k 10 870`, which would kill a drifting suite with an opaque
# rc=124 AFTER burning the whole budget. This guard fails the session
# loudly once the non-slow suite crosses REDCLIFF_T1_WALL_BUDGET_S
# (default 800 s — inside the 870 s hard kill so the message actually
# prints), and reports the elapsed/budget line every run so drift is
# visible long before it bites. Roadmap anchor: ~549 s warm-cache.
# ---------------------------------------------------------------------------
T1_WALL_BUDGET_S = float(os.environ.get("REDCLIFF_T1_WALL_BUDGET_S", "800"))
_SESSION_T0 = time.monotonic()


def _tier1_session(config):
    """True when this session is the tier-1 shape (slow tests deselected)."""
    return "not slow" in (config.getoption("markexpr", "") or "")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration test (full pipelines, "
        "multi-process runs)")


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    elapsed = time.monotonic() - _SESSION_T0
    if not _tier1_session(config):
        return
    terminalreporter.write_line(
        f"tier-1 wall clock: {elapsed:.0f}s "
        f"(budget {T1_WALL_BUDGET_S:.0f}s, hard kill at 870s)")
    if elapsed > T1_WALL_BUDGET_S:
        terminalreporter.write_line(
            f"tier-1 WALL-CLOCK GUARD: suite took {elapsed:.0f}s > "
            f"{T1_WALL_BUDGET_S:.0f}s budget — slow-mark the new offenders "
            f"before the 870s hard timeout starts eating CI", red=True)


def pytest_sessionfinish(session, exitstatus):
    elapsed = time.monotonic() - _SESSION_T0
    if _tier1_session(session.config) and elapsed > T1_WALL_BUDGET_S \
            and session.exitstatus == 0:
        # escalate 0 -> 1 only: never mask a real failure's exit status
        session.exitstatus = 1


def add_reference_to_path(extra_stubs=()):
    """Make /root/reference importable for the A/B parity suites: headless
    matplotlib, stub modules for import-time-only dependencies that are not
    installed (pywt always; torcheeg for the model-level suite), and the
    reference root on sys.path.  Returns the reference root."""
    import sys
    import types

    import matplotlib

    matplotlib.use("Agg")
    stubs = {"pywt": {"swt": None, "iswt": None, "Wavelet": None}}
    for name, attrs in extra_stubs:
        stubs[name] = attrs
    for name, attrs in stubs.items():
        if name not in sys.modules:
            m = types.ModuleType(name)
            for a, v in attrs.items():
                setattr(m, a, v)
            sys.modules[name] = m
    ref_root = "/root/reference"
    if ref_root not in sys.path:
        sys.path.append(ref_root)
    return ref_root
