"""NAVAR family: grouped-conv parity vs torch, additive-contribution semantics,
and end-to-end causal-score recovery on the synthetic sVAR oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import train_val_split
from redcliff_tpu.models.navar import NAVAR, NAVARConfig, NAVARLSTM, NAVARLSTMConfig
from redcliff_tpu.train.trainer import TrainConfig, Trainer
from redcliff_tpu.utils.metrics import roc_auc


def test_navar_forward_matches_torch_grouped_conv():
    """The batched einsum must reproduce the reference's grouped Conv1d
    architecture (ref navar.py:28-51) exactly."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    N, H, L, B = 4, 6, 3, 5
    model = NAVAR(NAVARConfig(num_nodes=N, num_hidden=H, maxlags=L))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    Xw = rng.normal(size=(B, L, N)).astype(np.float32)

    preds, contribs = model.forward(params, jnp.asarray(Xw))

    # torch grouped conv: weight (H*N, 1, L), block j*H:(j+1)*H is node j
    w1 = torch.tensor(np.asarray(params["w1"]).reshape(N * H, 1, L))
    b1 = torch.tensor(np.asarray(params["b1"]).reshape(N * H))
    xt = torch.tensor(np.swapaxes(Xw, 1, 2))  # (B, N, L)
    hidden = F.conv1d(xt, w1, b1, groups=N).clamp(min=0)
    hidden = hidden.transpose(-1, -2).reshape(-1, N, H)
    wc = torch.tensor(np.asarray(params["wc"]).reshape(N * N, 1, H))
    bc = torch.tensor(np.asarray(params["bc"]).reshape(N * N))
    out = F.conv1d(hidden, wc, bc, groups=N)
    out = out.view(-1, N, N, 1)
    t_preds = torch.sum(out, dim=1).squeeze(-1) + torch.tensor(
        np.asarray(params["bias"]))
    np.testing.assert_allclose(np.asarray(preds), t_preds.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(contribs), out[..., 0].numpy(),
                               rtol=1e-5, atol=1e-5)


def test_navar_predictions_are_contribution_sums():
    N, H, L = 3, 4, 2
    model = NAVAR(NAVARConfig(num_nodes=N, num_hidden=H, maxlags=L))
    params = model.init(jax.random.PRNGKey(1))
    Xw = jax.random.normal(jax.random.PRNGKey(2), (7, L, N))
    preds, contribs = model.forward(params, Xw)
    np.testing.assert_allclose(
        np.asarray(preds),
        np.asarray(contribs.sum(axis=1) + params["bias"]), rtol=1e-6)


def test_navar_lstm_shapes_and_loss():
    N, H, L = 3, 5, 6
    model = NAVARLSTM(NAVARLSTMConfig(num_nodes=N, num_hidden=H, maxlags=L,
                                      hidden_layers=2))
    params = model.init(jax.random.PRNGKey(3))
    X = jax.random.normal(jax.random.PRNGKey(4), (4, L + 1, N))
    preds, contribs = model.forward(params, X[:, :L, :])
    assert preds.shape == (4, L, N)
    assert contribs.shape == (4, L, N, N)
    combo, parts = model.loss(params, X)
    assert np.isfinite(float(combo))
    cm = model.causal_matrix(params, X)
    assert cm.shape == (N, N)


@pytest.fixture(scope="module")
def navar_data():
    D = 5
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=1, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=31,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=6,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(8), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=192,
        recording_length=24, burnin_period=10, num_labeled_sys_states=1,
        noise_type="gaussian", noise_amp=0.0,
    )
    return graphs, X, Y


def test_navar_end_to_end_recovers_structure(navar_data):
    graphs, X, Y = navar_data
    D = X.shape[2]
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                       rng=np.random.default_rng(0))
    model = NAVAR(NAVARConfig(num_nodes=D, num_hidden=12, maxlags=2, lambda1=0.2))
    params = model.init(jax.random.PRNGKey(0))
    trainer = Trainer(model, TrainConfig(learning_rate=5e-3, max_iter=30,
                                         batch_size=64, check_every=10, lookback=5))
    # true_GC exercises the data-dependent GC tracking path
    res = trainer.fit(params, train_ds, val_ds, true_GC=[graphs[0].sum(axis=2).T])
    fl = res.histories["avg_forecasting_loss"]
    assert fl[-1] < fl[0]
    assert res.tracker is not None
    assert len(res.tracker.f1score_histories[0.0][0]) == len(fl)
    # causal matrix is (source, target): compare against transposed truth
    cm = np.asarray(model.causal_matrix(res.params, jnp.asarray(train_ds.X)))
    truth = (graphs[0].sum(axis=2) > 0).astype(int).T
    auc = roc_auc(truth.ravel(), cm.ravel())
    assert auc > 0.8, f"ROC-AUC {auc} too close to chance"


def test_navar_dropout_is_active_in_training_step():
    """With dropout configured, the trainer threads an rng through the loss —
    two different seeds must produce different first-step losses, while rng=None
    (eval mode) is deterministic."""
    N, H, L = 3, 8, 2
    model = NAVAR(NAVARConfig(num_nodes=N, num_hidden=H, maxlags=L, dropout=0.5))
    assert model.wants_rng
    params = model.init(jax.random.PRNGKey(0))
    X = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (8, 10, N)))
    l1, _ = model.loss(params, X, rng=jax.random.PRNGKey(2))
    l2, _ = model.loss(params, X, rng=jax.random.PRNGKey(3))
    le1, _ = model.loss(params, X)
    le2, _ = model.loss(params, X)
    assert float(l1) != float(l2)
    assert float(le1) == float(le2)


def test_navar_lstm_uses_full_sequence():
    """The LSTM variant consumes the full recording (ref navar.py:216-222), so
    recordings of different lengths produce different contribution streams."""
    N, H = 3, 5
    model = NAVARLSTM(NAVARLSTMConfig(num_nodes=N, num_hidden=H, maxlags=2))
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(1), (4, 20, N))
    cm_full = model.causal_matrix(params, X)
    cm_short = model.causal_matrix(params, X[:, :5, :])
    assert not np.allclose(np.asarray(cm_full), np.asarray(cm_short))
