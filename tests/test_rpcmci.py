"""Unsupervised regime-PCMCI (native tigramite-RPCMCI capability) and the
notebook's D4IC regime experiment driver."""
import numpy as np
import pytest

from redcliff_tpu.eval.supervised_discovery import (
    run_d4ic_regime_pcmci_experiment)
from redcliff_tpu.models.pcmci import pcmci_val_graph, rpcmci


def _var_recording(A, T, rng, noise=0.1):
    N = A.shape[0]
    x = np.zeros((T, N))
    x[0] = rng.normal(size=N)
    for t in range(1, T):
        x[t] = A @ x[t - 1] + noise * rng.normal(size=N)
    return x


@pytest.fixture(scope="module")
def two_regime_data():
    # regime 0: x0 drives x1; regime 1: x1 drives x0 (+ weak self-decay)
    A0 = np.array([[0.5, 0.0, 0.0], [0.8, 0.3, 0.0], [0.0, 0.0, 0.4]])
    A1 = np.array([[0.3, 0.8, 0.0], [0.0, 0.5, 0.0], [0.0, 0.0, 0.4]])
    rng = np.random.default_rng(0)
    recs, labels = [], []
    for i in range(16):
        k = i % 2
        recs.append(_var_recording(A0 if k == 0 else A1, 80, rng))
        labels.append(k)
    return recs, np.asarray(labels)


def test_rpcmci_recovers_recording_regimes(two_regime_data):
    recs, labels = two_regime_data
    out = rpcmci(recs, num_regimes=2, tau_max=1, seed=0)
    assign = np.asarray(out["assignment"])
    # perfect clustering up to label permutation
    agree = max((assign == labels).mean(), (assign != labels).mean())
    assert agree == 1.0, (assign, labels)
    # per-regime graphs recover the planted directed edge as the strongest
    # off-diagonal link (val graph entry (i, j) = X_i -> X_j)
    tops = set()
    for k in (0, 1):
        val = pcmci_val_graph(out["results"][k], alpha_level=0.05)
        off = val * (1 - np.eye(3))
        tops.add(divmod(int(off.argmax()), 3))
    assert tops == {(0, 1), (1, 0)}
    assert np.isfinite(out["error"])


def test_rpcmci_skips_short_recordings_without_misalignment(two_regime_data):
    """Recordings shorter than tau_max are excluded (-1 in the assignment)
    and must not shift other recordings' labels (index-alignment
    regression)."""
    recs, labels = two_regime_data
    rng = np.random.default_rng(9)
    mixed = [rng.normal(size=(1, 3))] + recs[:8] + [rng.normal(size=(1, 3))]
    out = rpcmci(mixed, num_regimes=2, tau_max=1, seed=0)
    assign = np.asarray(out["assignment"])
    assert len(assign) == len(mixed)
    assert assign[0] == -1 and assign[-1] == -1
    kept = assign[1:-1]
    agree = max((kept == labels[:8]).mean(), (kept != labels[:8]).mean())
    assert agree == 1.0

    # timestep mode: excluded recordings appear as None paths
    out_t = rpcmci(mixed, num_regimes=2, tau_max=1, assign_per="timestep",
                   switching_penalty=10.0, seed=0)
    paths = out_t["assignment"]
    assert paths[0] is None and paths[-1] is None
    assert all(p is not None and len(p) == 79 for p in paths[1:-1])


def test_rpcmci_timestep_mode_finds_switch():
    A0 = np.array([[0.5, 0.0], [0.9, 0.3]])
    A1 = np.array([[0.3, 0.9], [0.0, 0.5]])
    rng = np.random.default_rng(1)
    first = _var_recording(A0, 150, rng)
    second = _var_recording(A1, 150, rng)
    series = np.concatenate([first, second])
    out = rpcmci([series], num_regimes=2, tau_max=1, assign_per="timestep",
                 switching_penalty=5.0, seed=0)
    path = out["assignment"][0]
    assert len(path) == 299  # T - tau_max
    # each half dominated by one regime, different between halves
    first_mode = np.bincount(path[:120]).argmax()
    second_mode = np.bincount(path[-120:]).argmax()
    assert first_mode != second_mode
    assert (path[:120] == first_mode).mean() > 0.8
    assert (path[-120:] == second_mode).mean() > 0.8
    # the switching penalty keeps the path piecewise-constant
    assert (np.diff(path) != 0).sum() <= 10


@pytest.fixture(scope="module")
def d4ic_like_samples():
    A0 = np.array([[0.5, 0.0, 0.0], [0.8, 0.3, 0.0], [0.0, 0.2, 0.4]])
    A1 = np.array([[0.3, 0.8, 0.0], [0.0, 0.5, 0.0], [0.6, 0.0, 0.4]])
    rng = np.random.default_rng(2)
    samples = []
    for i in range(16):
        k = i % 2
        x = _var_recording(A0 if k == 0 else A1, 60, rng)
        y = np.zeros((2, 60))
        y[k] = 1.0  # dominant-network coefficient trace
        samples.append((x.astype(np.float32), y.astype(np.float32)))
    # VAR transition A[i, j] = x_j drives x_i, which IS the
    # columns-drive-rows convention the transposed predictions use
    truths = [(np.abs(A) * (1 - np.eye(3)) > 0.1).astype(float)
              for A in (A0, A1)]
    return samples, truths


@pytest.mark.parametrize("pred_source", ["graph", "val_matrix"])
def test_d4ic_experiment_oracle_regimes(d4ic_like_samples, pred_source):
    samples, truths = d4ic_like_samples
    out = run_d4ic_regime_pcmci_experiment(
        samples, truths, regime_source="oracle", pred_source=pred_source,
        transpose=True, tau_max=2)
    assert set(out["optF1Scores_by_regime"]) == {0, 1}
    assert 0.0 <= out["cross_regime_mean"] <= 1.0
    # planted 2-edge graphs on clean VAR data: discovery should do well
    assert out["cross_regime_mean"] > 0.6, out["optF1Scores_by_regime"]
    assert np.isfinite(out["cross_regime_sem"])


def test_d4ic_experiment_learned_regimes(d4ic_like_samples):
    samples, truths = d4ic_like_samples
    out = run_d4ic_regime_pcmci_experiment(
        samples, truths, regime_source="learned", pred_source="graph",
        transpose=True, tau_max=2)
    # unsupervised regimes + Hungarian alignment should still beat chance
    assert out["cross_regime_mean"] > 0.6, out["optF1Scores_by_regime"]
    assert set(out["preds_by_regime"]) == {0, 1}
