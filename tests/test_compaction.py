"""Elastic grid scheduler acceptance battery (parallel/compaction.py +
runtime/compileobs.py): live-lane compaction must be BIT-identical to the
fixed-width run — per-lane params, metrics, and failures under original
point ids — including across a mid-run SIGKILL resume that crosses a
compaction boundary; bucket-padding filler lanes must never leak into
GridResult; the persistent compile cache must serve warm programs; and a
steady-state recompile tripwire pins "two epochs after warmup compile
nothing" for future PRs.
"""
import os
import pickle
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest

from redcliff_tpu.parallel import compaction
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.runtime import compileobs
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
from redcliff_tpu.utils.observability import read_jsonl
from test_parallel_grid import _data, _model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = [sys.executable, "-m", "redcliff_tpu.runtime.faultinject"]


# ---------------------------------------------------------------------------
# pure planning units
# ---------------------------------------------------------------------------
def test_bucket_width_ladder():
    assert compaction.next_pow2(0) == 1
    assert compaction.next_pow2(1) == 1
    assert compaction.next_pow2(5) == 8
    assert compaction.next_pow2(16) == 16
    # no mesh: plain powers of two
    assert compaction.bucket_width(3) == 4
    assert compaction.bucket_width(9) == 16
    # width >= mesh: multiple of the device count (no-op on pow2 meshes)
    assert compaction.bucket_width(9, n_devices=8) == 16
    assert compaction.bucket_width(16, n_devices=8) == 16
    assert compaction.bucket_width(9, n_devices=6) == 18
    # width < mesh: a divisor runs on a sub-mesh, otherwise pad to the mesh
    assert compaction.bucket_width(2, n_devices=8) == 2
    assert compaction.bucket_width(3, n_devices=8) == 4
    assert compaction.bucket_width(3, n_devices=6) == 6


def test_plan_compaction_orders_and_retires():
    active = np.array([False, True, False, True, False, False, True, False])
    orig = np.arange(8, dtype=np.int32)
    plan = compaction.plan_compaction(active, orig, retired_ids=[0])
    assert plan.new_width == 4  # 3 live -> bucket 4
    # survivors keep exec-row order; filler replicates the first survivor
    np.testing.assert_array_equal(plan.sel, [1, 3, 6, 1])
    np.testing.assert_array_equal(plan.orig_ids, [1, 3, 6, -1])
    np.testing.assert_array_equal(plan.active, [True, True, True, False])
    # inactive real lanes retire once (0 was already retired earlier)
    np.testing.assert_array_equal(sorted(plan.retire_ids), [2, 4, 5, 7])
    # a half-filler grid still trims down the ladder (4 -> 2)
    trim = compaction.plan_compaction(
        np.array([True, True, False, False]),
        np.array([0, 1, -1, -1], np.int32), retired_ids=[])
    assert trim.new_width == 2 and trim.retire_rows.size == 0
    np.testing.assert_array_equal(trim.orig_ids, [0, 1])
    # already at the right bucket -> no plan
    assert compaction.plan_compaction(
        np.array([True, True]), np.array([0, 1], np.int32),
        retired_ids=[]) is None
    # nothing live -> no plan (the fit's own exit paths own this case)
    assert compaction.plan_compaction(
        np.zeros(4, bool), orig[:4], retired_ids=[]) is None


def test_expand_history_carries_retired_lanes_forward():
    eras = [np.array([0, 1, 2, 3], np.int32), np.array([1, 3], np.int32)]
    rows = [np.array([1., 2., 3., 4.]), np.array([1.5, 2.5, 3.5, 4.5]),
            np.array([20., 40.]), np.array([21., 41.])]
    out = compaction.expand_history(rows, [0, 0, 1, 1], eras, 4)
    np.testing.assert_array_equal(out[1], [1.5, 2.5, 3.5, 4.5])
    # lanes 0/2 were dropped after epoch 1: their value carries forward,
    # which IS the uncompacted semantics (frozen params -> identical loss)
    np.testing.assert_array_equal(out[2], [1.5, 20., 3.5, 40.])
    np.testing.assert_array_equal(out[3], [1.5, 21., 3.5, 41.])
    # full-width rows (restored from a checkpoint) pass through as-is
    out2 = compaction.expand_history(
        [np.arange(4.), np.array([9., 9.])],
        [-1, 1], eras, 4)
    np.testing.assert_array_equal(out2[1], [0., 9., 2., 9.])


# ---------------------------------------------------------------------------
# THE acceptance property: compaction ON == compaction OFF, bit for bit
# ---------------------------------------------------------------------------
def test_compaction_bit_identity_g16_early_stop_and_quarantine(tmp_path):
    """Seeded G=16 fit where 8 lanes early-stop (zero lr, patience 1) and 2
    quarantine (poison lr -> non-finite): per-lane final params, metrics
    (val_history/criteria/epochs), active masks, and failure records with
    compaction ON equal the fixed-width compaction-OFF run exactly. Also
    asserts the scheduler actually compacted and logged it (this is not a
    vacuous pass), and that metrics.jsonl carries the new lanes_live /
    grid_width / compaction observability."""
    import dataclasses

    model = _model()
    # 6 live + 8 zero-lr early-stoppers + 2 poison-lr quarantines = 16
    points = ([{"gen_lr": 1e-3 * (1 + i)} for i in range(6)]
              + [{"gen_lr": 0.0, "embed_lr": 0.0}] * 8
              + [{"gen_lr": 1e20, "embed_lr": 1e20}] * 2)
    spec = GridSpec(points=points)
    ds = _data(model)
    key = jax.random.PRNGKey(7)
    tc = RedcliffTrainConfig(max_iter=5, batch_size=32, lookback=1,
                             check_every=1)
    log_on = str(tmp_path / "on")
    r_on = RedcliffGridRunner(model, tc, spec)
    res_on = r_on.fit(key, ds, ds, log_dir=log_on)
    r_off = RedcliffGridRunner(
        model, dataclasses.replace(tc, compaction=False), spec)
    res_off = r_off.fit(key, ds, ds)

    assert r_on.dispatch_stats["compactions"] >= 1
    assert r_on.dispatch_stats["grid_width"] < 16
    assert r_off.dispatch_stats["compactions"] == 0
    assert r_on.dispatch_stats["lane_epochs"] \
        < r_on.dispatch_stats["lane_epochs_nominal"]
    # >= 6 lanes actually retired mid-run, as the property demands
    assert int((~res_on.active).sum()) >= 6

    np.testing.assert_array_equal(res_on.val_history, res_off.val_history)
    np.testing.assert_array_equal(res_on.best_criteria,
                                  res_off.best_criteria)
    np.testing.assert_array_equal(res_on.best_epoch, res_off.best_epoch)
    np.testing.assert_array_equal(res_on.active, res_off.active)
    assert res_on.failures == res_off.failures
    assert {f["point"] for f in res_on.failures} == {14, 15}
    # params: xla's NEW cpu thunk runtime (the jax default this suite runs
    # under) emits scan bodies whose codegen depends on the program width,
    # rounding a handful of weights ~1 ulp differently across widths — the
    # legacy runtime and the per-batch program are width-EXACT (see
    # test_compaction_bit_identity_exact_on_width_stable_runtime, which
    # pins full bitwise equality on that runtime). Here: tight float
    # equality, plus bitwise on everything decision-shaped above
    for a, b in zip(jax.tree.leaves(res_on.best_params),
                    jax.tree.leaves(res_off.best_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # observability: epoch records carry lane occupancy, and the compaction
    # event + per-program compile costs landed in metrics.jsonl
    events = read_jsonl(log_on)
    epochs = [e for e in events if e.get("event") == "epoch"]
    assert epochs and all("lanes_live" in e and "grid_width" in e
                          for e in epochs)
    comps = [e for e in events if e.get("event") == "compaction"]
    assert comps and comps[0]["to_width"] < comps[0]["from_width"]
    assert comps[0]["retired"] == sorted(comps[0]["retired"])
    compiles = [e for e in events if e.get("event") == "compile"]
    assert compiles and all(e["compile_ms"] > 0 for e in compiles)


def test_filler_lanes_never_leak_into_grid_result():
    """A non-power-of-two grid (G=3 -> width-4 bucket) reports results at
    the REAL width everywhere, including when a real lane quarantines: no
    phantom point ids, no filler rows in any result field."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3},
                            {"gen_lr": 1e20, "embed_lr": 1e20}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32, check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(1), ds, ds)
    assert runner.dispatch_stats["grid_width"] in (1, 2, 4)
    assert runner.dispatch_stats["lanes_real"] == 3
    assert res.val_history.shape[1] == 3
    assert res.best_criteria.shape == (3,)
    assert res.active.shape == (3,)
    assert jax.tree.leaves(res.best_params)[0].shape[0] == 3
    assert {f["point"] for f in res.failures} <= {0, 1, 2}
    assert [f["point"] for f in res.failures] == [2]
    assert {k: v.shape for k, v in res.coeffs.items()} \
        == {k: (3,) for k in res.coeffs}


_STRICT_CHILD = r"""
import os, sys
sys.path.insert(0, os.path.join({repo!r}, "tests"))
import numpy as np, jax, dataclasses
jax.config.update("jax_platforms", "cpu")
from test_parallel_grid import _model, _data
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

model = _model()
# 2 live + 3 early-stop + 1 quarantine = 6 points -> width-8 bucket, then
# compaction to width 2 once the retirements land
points = ([{{"gen_lr": 1e-3}}, {{"gen_lr": 3e-3}}]
          + [{{"gen_lr": 0.0, "embed_lr": 0.0}}] * 3
          + [{{"gen_lr": 1e20, "embed_lr": 1e20}}])
spec = GridSpec(points=points)
ds = _data(model, n=48)
tc = RedcliffTrainConfig(max_iter=4, batch_size=16, lookback=1,
                         check_every=1)
key = jax.random.PRNGKey(7)
r_on = RedcliffGridRunner(model, tc, spec)
res_on = r_on.fit(key, ds, ds)
assert r_on.dispatch_stats["compactions"] >= 1, r_on.dispatch_stats
assert r_on.dispatch_stats["grid_width"] == 2, r_on.dispatch_stats
r_off = RedcliffGridRunner(
    model, dataclasses.replace(tc, compaction=False), spec)
res_off = r_off.fit(key, ds, ds)
np.testing.assert_array_equal(res_on.val_history, res_off.val_history)
np.testing.assert_array_equal(res_on.best_criteria, res_off.best_criteria)
np.testing.assert_array_equal(res_on.best_epoch, res_off.best_epoch)
np.testing.assert_array_equal(res_on.active, res_off.active)
assert res_on.failures == res_off.failures
for a, b in zip(jax.tree.leaves(res_on.best_params),
                jax.tree.leaves(res_off.best_params)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
print("STRICT-BIT-IDENTITY-OK")
"""


def test_compaction_bit_identity_exact_on_width_stable_runtime(tmp_path):
    """FULL bitwise identity — per-lane params included — of compaction ON
    vs OFF, on a backend whose codegen is width-stable (XLA's legacy CPU
    runtime; the new thunk runtime rounds scan bodies ~1 ulp differently
    per program width, see the in-process test above). This is the
    tentpole's bit-identity claim pinned end to end: early stop +
    quarantine + bucket padding + compaction 8 -> 2."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_cpu_use_thunk_runtime=false").strip()
    env.pop("REDCLIFF_FAULT_INJECT", None)
    r = subprocess.run(
        [sys.executable, "-c", _STRICT_CHILD.format(repo=REPO)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-4000:]
    assert "STRICT-BIT-IDENTITY-OK" in r.stdout


# ---------------------------------------------------------------------------
# SIGKILL resume across a compaction boundary
# ---------------------------------------------------------------------------
def test_sigkill_resume_across_compaction_boundary(tmp_path):
    """The canonical tiny fit with a poison point quarantines lane 1 and
    compacts 2 -> 1 at the first check window. SIGKILLing right after the
    epoch-2 checkpoint (inside the compacted era) and resuming must land in
    the same bucket and finish bit-identical to an uninterrupted run —
    the 'compaction events checkpointed' contract, end to end."""

    def run_child(ck, *extra, fault=None, timeout=240):
        env = dict(os.environ)
        env.pop("REDCLIFF_FAULT_INJECT", None)
        if fault:
            env["REDCLIFF_FAULT_INJECT"] = fault
        return subprocess.run(
            CHILD + ["--checkpoint-dir", str(ck), "--bad-point"]
            + list(extra),
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)

    ck = tmp_path / "ck"
    killed = run_child(ck, "--max-iter", "4",
                       fault="sigkill_after_checkpoint:2")
    assert killed.returncode == -signal.SIGKILL, killed.stderr[-2000:]
    ckpt = rck.read_checkpoint(str(ck / "grid_checkpoint.pkl"))
    assert ckpt["epoch"] == 2
    # the checkpoint was written INSIDE the compacted era: one-lane width,
    # lane->point map and the retired lane's frozen results on board
    assert len(ckpt["orig_ids"]) == 1
    assert 1 in ckpt["retired"]

    res_path = tmp_path / "resumed.pkl"
    resumed = run_child(ck, "--max-iter", "4", "--result", str(res_path))
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    full_path = tmp_path / "full.pkl"
    uninterrupted = run_child(tmp_path / "ck_full", "--max-iter", "4",
                              "--result", str(full_path))
    assert uninterrupted.returncode == 0, uninterrupted.stderr[-2000:]

    with open(res_path, "rb") as f:
        got = pickle.load(f)
    with open(full_path, "rb") as f:
        want = pickle.load(f)
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    np.testing.assert_array_equal(got["best_criteria"],
                                  want["best_criteria"])
    np.testing.assert_array_equal(got["best_epoch"], want["best_epoch"])
    np.testing.assert_array_equal(got["active"], want["active"])
    assert got["failures"] == want["failures"]
    assert [f["point"] for f in got["failures"]] == [1]
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# steady-state recompile tripwire + persistent compile cache
# ---------------------------------------------------------------------------
def test_steady_state_zero_recompiles_after_warmup():
    """CI tripwire: once a fit has warmed every program, further epochs (a
    whole second fit here — strictly stronger than 'two epochs after
    warmup') must trigger ZERO new XLA compilations. A future PR that
    silently reintroduces per-epoch or per-fit recompiles fails here."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    runner.fit(jax.random.PRNGKey(0), ds, ds)  # warmup: compiles everything
    before = compileobs.snapshot()
    runner.fit(jax.random.PRNGKey(0), ds, ds)  # steady state
    d = compileobs.delta(before)
    assert d["compiles"] == 0, (
        f"steady-state epochs recompiled {d['compiles']} program(s) "
        f"({d['compile_ms']} ms) — a dispatch in the hot loop is "
        f"jit-specializing on something that changes per epoch/fit")
    assert runner.dispatch_stats["compiles"] == 0


def test_persistent_compile_cache_warm_start(tmp_path):
    """enable_cache points jax at a VERSIONED cache dir; clearing the
    in-memory executable caches and re-compiling an identical program is
    served from disk (cache_hits) rather than recompiled from scratch."""
    import jax.numpy as jnp

    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        cache_dir = compileobs.enable_cache(str(tmp_path / "cc"))
        assert compileobs.cache_version_tag() in cache_dir
        assert jax.__version__ in os.path.basename(cache_dir)

        @jax.jit
        def f(x):
            return jnp.sin(x) @ jnp.cos(x.T) + 3.0

        x = jnp.ones((32, 32))
        before = compileobs.snapshot()
        f(x).block_until_ready()
        cold = compileobs.delta(before)
        assert cold["compiles"] >= 1 and cold["cache_misses"] >= 1
        jax.clear_caches()
        before = compileobs.snapshot()
        f(x).block_until_ready()
        warm = compileobs.delta(before)
        assert warm["cache_hits"] >= 1
        assert warm["cache_misses"] == 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        # conftest's suite-wide cache dir may have been displaced; restore
        compileobs.enable_cache()


# ---------------------------------------------------------------------------
# compaction vs the wall-clock deadline machinery
# ---------------------------------------------------------------------------
def test_deadline_eviction_after_compaction_reports_original_ids(tmp_path):
    """A lane deadline firing AFTER a compaction must evict the right lane
    and report it under its ORIGINAL point id (the deadline arrays are
    era-remapped on compaction)."""
    model = _model()
    # lane 1 early-stops (compaction 4 -> smaller); lane 3's deadline then
    # fires on the compacted grid
    spec = GridSpec(
        points=[{"gen_lr": 1e-3}, {"gen_lr": 0.0, "embed_lr": 0.0},
                {"gen_lr": 2e-3}, {"gen_lr": 3e-3}],
        fit_deadline_s=[np.inf, np.inf, np.inf, 1e-6])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, lookback=1,
                             check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(3), ds, ds,
                     checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4)
    dl = [f for f in res.failures if f["cause"] == "deadline"]
    assert [f["point"] for f in dl] == [3]
    assert not res.active[1] and not res.active[3]
