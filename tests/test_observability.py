"""Structured observability (SURVEY §5): jsonl metric logging schema and the
opt-in profiler hook, replacing the reference's stdout-scrape observability
(ref README.md:96, redcliff_s_cmlp.py:1549-1569).

The telemetry spine grew out of this module (redcliff_tpu/obs,
docs/ARCHITECTURE.md "Telemetry spine"); this file pins its primitives:
span semantics (parent propagation, zero-cost disabled path, no host sync by
construction), the flight-recorder rings + dump artifact, the seq/pid/host
identity triple, torn-tail-tolerant reads, size-capped rotation, and the
schema validator. The end-to-end report/tripwire suite lives in
tests/test_obs_report.py. The imports below deliberately go through the
utils.observability back-compat shim where the original API is exercised.
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from redcliff_tpu import obs
from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import train_val_split
from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
from redcliff_tpu.obs import flight, schema, spans
from redcliff_tpu.train.trainer import TrainConfig, Trainer
from redcliff_tpu.utils.observability import (
    MetricLogger, jsonable, profiler_trace, read_jsonl)


def test_jsonable_coerces_numpy_and_dataclasses():
    cfg = TrainConfig(learning_rate=0.5)
    out = jsonable({
        "int": np.int64(3),
        "float": np.float32(1.5),
        "arr": np.arange(4).reshape(2, 2),
        "jax": jax.numpy.ones((2,)),
        "cfg": cfg,
        "nested": [np.float64(2.0), ("a", np.int32(1))],
    })
    assert out["int"] == 3 and isinstance(out["int"], int)
    assert out["float"] == 1.5 and isinstance(out["float"], float)
    assert out["arr"] == [[0, 1], [2, 3]]
    assert out["jax"] == [1.0, 1.0]
    assert out["cfg"]["learning_rate"] == 0.5
    assert out["nested"] == [2.0, ["a", 1]]
    json.dumps(out)  # round-trips through the encoder


def test_metric_logger_writes_and_reads(tmp_path):
    with MetricLogger(str(tmp_path)) as log:
        assert log.active
        log.log("epoch", epoch=0, loss=np.float32(1.25))
        log.log("epoch", epoch=1, loss=0.5)
        log.log("fit_end", best_it=1)
    recs = read_jsonl(str(tmp_path))
    assert [r["event"] for r in recs] == ["epoch", "epoch", "fit_end"]
    assert all("wall_time" in r for r in recs)
    assert recs[0]["loss"] == 1.25
    epochs = read_jsonl(str(tmp_path), event="epoch")
    assert len(epochs) == 2

    # resume appends rather than truncating
    with MetricLogger(str(tmp_path)) as log:
        log.log("fit_start", resume_epoch=2)
    assert len(read_jsonl(str(tmp_path))) == 4


def test_metric_logger_none_is_noop():
    log = MetricLogger(None)
    assert not log.active
    log.log("epoch", epoch=0)  # must not raise
    log.close()


def test_trainer_emits_epoch_schema(tmp_path):
    D = 4
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=1, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=3,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=4,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(0), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=64,
        recording_length=24, burnin_period=5, num_labeled_sys_states=1)
    train_ds, val_ds = train_val_split(np.asarray(X), np.asarray(Y),
                                       val_fraction=0.25,
                                       rng=np.random.default_rng(0))
    model = CMLPFM(CMLPFMConfig(num_chans=D, gen_lag=2, gen_hidden=(8,),
                                input_length=8))
    params = model.init(jax.random.PRNGKey(1))
    run = str(tmp_path / "run")
    trainer = Trainer(model, TrainConfig(learning_rate=1e-3, max_iter=3,
                                         batch_size=32, check_every=1))
    trainer.fit(params, train_ds, val_ds, true_GC=[graphs[0]], save_dir=run)

    recs = read_jsonl(run)
    events = [r["event"] for r in recs]
    assert events[0] == "fit_start"
    assert events[-1] == "fit_end"
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert len(epochs) == 3
    for i, r in enumerate(epochs):
        assert r["epoch"] == i
        assert isinstance(r["combo_loss"], float)
        assert isinstance(r["criteria"], float)
        # GC-vs-oracle metrics flattened in when a tracker is active
        assert "f1_t0.0_factor0" in r
        assert "roc_auc_t0.0_factor0" in r
        assert "deltacon0_factor0" in r
    start = recs[0]
    assert start["model"] == "CMLPFM"
    assert start["train_config"]["max_iter"] == 3
    end = recs[-1]
    assert set(end) >= {"best_it", "best_loss", "final_val_loss"}

    # the file is line-delimited JSON (the structured-logging contract)
    with open(os.path.join(run, "metrics.jsonl")) as f:
        for line in f:
            json.loads(line)


def test_metric_logger_stamps_identity_triple(tmp_path):
    """Every record carries seq/pid/host; seq is monotonic across two
    loggers in one process (total order for interleaved writers)."""
    with MetricLogger(str(tmp_path / "a")) as la, \
            MetricLogger(str(tmp_path / "b")) as lb:
        la.log("epoch", epoch=0)
        lb.log("epoch", epoch=0)
        la.log("fit_end")
    ra = read_jsonl(str(tmp_path / "a"))
    rb = read_jsonl(str(tmp_path / "b"))
    for r in ra + rb:
        assert r["pid"] == os.getpid()
        assert isinstance(r["host"], str) and r["host"]
        assert isinstance(r["seq"], int)
    assert ra[0]["seq"] < rb[0]["seq"] < ra[1]["seq"]


def test_read_jsonl_tolerates_torn_tail(tmp_path):
    """A line torn by a crash mid-append is skipped and counted instead of
    poisoning the file; strict=True restores raise-on-bad-line."""
    with MetricLogger(str(tmp_path)) as log:
        for i in range(3):
            log.log("epoch", epoch=i)
    path = tmp_path / "metrics.jsonl"
    with open(path, "a") as f:
        f.write('{"event": "epoch", "epoch": 3, "wall_ti')  # torn tail
    stats = {}
    recs = read_jsonl(str(tmp_path), stats=stats)
    assert [r["epoch"] for r in recs] == [0, 1, 2]
    assert stats["torn_lines"] == 1 and stats["records"] == 3
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(str(tmp_path), strict=True)


def test_read_jsonl_crash_mid_write(tmp_path):
    """A REAL SIGKILL mid-append: the child flushes half a record and kills
    itself with the line unterminated — exactly the on-disk state a
    preemption leaves; readers must keep working."""
    child = (
        "import os, signal\n"
        "from redcliff_tpu.obs import MetricLogger\n"
        f"log = MetricLogger({str(tmp_path)!r})\n"
        "log.log('fit_start', model='X')\n"
        "log.log('epoch', epoch=0)\n"
        "log._fh.write('{\"event\": \"epoch\", \"epoch\": 1, \"wall')\n"
        "log._fh.flush()\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    r = subprocess.run([sys.executable, "-c", child],
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))),
                       timeout=120)
    assert r.returncode == -9
    stats = {}
    recs = read_jsonl(str(tmp_path), stats=stats)
    assert [r["event"] for r in recs] == ["fit_start", "epoch"]
    assert stats["torn_lines"] == 1
    # the report CLI reads the same dir without raising
    from redcliff_tpu.obs import build_report

    rep = build_report(str(tmp_path))
    assert rep["read_audit"]["metrics"]["torn_lines"] == 1


def test_metric_logger_rotation(tmp_path):
    """Size-capped rotation: metrics.jsonl.1... appear, record order is
    preserved across the chain, no record is split across files."""
    with MetricLogger(str(tmp_path), max_bytes=400, max_backups=20) as log:
        for i in range(40):
            log.log("epoch", epoch=i)
    names = sorted(os.listdir(tmp_path))
    assert "metrics.jsonl" in names and "metrics.jsonl.1" in names
    recs = read_jsonl(str(tmp_path))
    assert [r["epoch"] for r in recs] == list(range(40))
    # every file in the chain is whole-line strict JSON
    for name in names:
        with open(tmp_path / name) as f:
            for line in f:
                json.loads(line)


def test_metric_logger_rotation_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("REDCLIFF_METRICS_MAX_BYTES", "300")
    with MetricLogger(str(tmp_path)) as log:
        assert log.max_bytes == 300
        for i in range(20):
            log.log("epoch", epoch=i)
    assert os.path.exists(tmp_path / "metrics.jsonl.1")


def test_metric_logger_rotation_drops_oldest(tmp_path):
    with MetricLogger(str(tmp_path), max_bytes=200, max_backups=2) as log:
        for i in range(60):
            log.log("epoch", epoch=i)
    names = {n for n in os.listdir(tmp_path) if n.startswith("metrics")}
    assert names <= {"metrics.jsonl", "metrics.jsonl.1", "metrics.jsonl.2"}
    recs = read_jsonl(str(tmp_path))
    # the newest records survive; order within the surviving chain holds
    epochs = [r["epoch"] for r in recs]
    assert epochs == sorted(epochs) and epochs[-1] == 59


# ---------------------------------------------------------------------------
# trace spans + flight recorder + counters (redcliff_tpu/obs)
# ---------------------------------------------------------------------------
def test_span_disabled_is_shared_noop():
    """REDCLIFF_TRACE=0 semantics: span() returns ONE shared no-op object —
    the zero-cost-when-disabled contract (one flag check, no allocation)."""
    was = obs.enabled()
    try:
        obs.set_enabled(False)
        assert obs.span("grid.dispatch") is obs.NOOP
        assert obs.span("x", kind="y") is obs.NOOP
        assert obs.record_span("x", 1.0) is None
        with obs.span("noop.scope") as sp:
            sp.set(extra=1)  # uniform API on the disabled path
    finally:
        obs.set_enabled(was)


def test_span_records_parent_chain_and_ring(tmp_path):
    flight.clear()
    with obs.span("ckpt.write", component="ckpt", file="a.pkl") as outer:
        with obs.span("ckpt.fsync") as inner:
            pass
    ring = flight.snapshot()["ckpt"]
    by_name = {r["name"]: r for r in ring}
    assert by_name["ckpt.fsync"]["parent_id"] == by_name["ckpt.write"][
        "span_id"]
    assert by_name["ckpt.write"]["attrs"]["file"] == "a.pkl"
    for r in ring:
        assert r["dur_ms"] >= 0 and r["pid"] == os.getpid()
        assert "t_wall" in r and "t_mono" in r
    assert outer.dur_ms >= inner.dur_ms


def test_span_emit_writes_schema_valid_event(tmp_path):
    flight.clear()
    with MetricLogger(str(tmp_path)) as log:
        with obs.span("grid.check_window", logger=log, emit=True,
                      epoch=3, width=8):
            pass
        obs.record_span("grid.compaction", 12.5, logger=log, emit=True,
                        epoch=3, from_width=8, to_width=4)
    recs = read_jsonl(str(tmp_path), event="span")
    assert [r["name"] for r in recs] == ["grid.check_window",
                                         "grid.compaction"]
    assert recs[0]["attrs"]["epoch"] == 3
    assert not schema.validate_records(recs)


def test_span_ring_is_bounded():
    rec = flight.FlightRecorder(capacity=5)
    for i in range(20):
        rec.record("c", {"i": i})
    ring = rec.snapshot()["c"]
    assert len(ring) == 5 and [r["i"] for r in ring] == list(range(15, 20))


def test_counters_delta():
    c = spans.Counters()
    before = c.snapshot()
    c.add("prefetch_stall_ms", 2.5)
    c.add("prefetch_stall_ms", 1.5)
    c.add("prefetch_items")
    d = c.delta(before)
    assert d["prefetch_stall_ms"] == 4.0 and d["prefetch_items"] == 1.0


def test_flight_dump_artifact_is_strict_json(tmp_path):
    flight.clear()
    with obs.span("prefetch.fill", component="prefetch", batch=7):
        pass
    p = flight.dump(str(tmp_path), reason="hang",
                    extra={"components": {"prefetch": {"age_s": 9.0}},
                           "bad_float": float("nan")})
    assert os.path.basename(p) == "flight_record.json"
    with open(p) as f:
        fr = json.load(f)  # strict parser: NaN would fail
    assert fr["reason"] == "hang" and fr["event"] == "flight_record"
    assert fr["extra"]["bad_float"] is None
    names = [r["name"] for r in fr["components"]["prefetch"]]
    assert "prefetch.fill" in names
    # the artifact itself validates as a flight_record event
    assert not schema.validate_record(fr)


def test_flight_dump_for_logger_and_inactive(tmp_path):
    assert flight.dump_for_logger(None, "hang") is None
    assert flight.dump_for_logger(MetricLogger(None), "hang") is None
    with MetricLogger(str(tmp_path)) as log:
        p = flight.dump_for_logger(log, "numerics_abort")
    assert p == str(tmp_path / "flight_record.json")


def test_spans_never_touch_jax():
    """No-host-sync tripwire at the source level: the span/flight hot path
    (and the post-mortem trace exporter, ISSUE 9) must never import jax or
    call block_until_ready — a device sync inside tracing would silently
    serialize every dispatch it wraps."""
    import ast

    import redcliff_tpu.obs.flight as fmod
    import redcliff_tpu.obs.spans as smod
    import redcliff_tpu.obs.trace_export as tmod

    for mod in (smod, fmod, tmod):
        with open(mod.__file__) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            elif isinstance(node, ast.Attribute):
                assert node.attr != "block_until_ready", mod.__name__
                continue
            else:
                continue
            assert not any(n.split(".")[0] == "jax" for n in names), \
                mod.__name__


def test_device_obs_modules_keep_jax_lazy():
    """ISSUE 9 satellite: the PR 7 no-host-sync tripwire extends to the new
    device-observatory modules — obs/memory.py and obs/profiling.py may use
    jax (memory_stats polls, profiler start/stop) but only via in-function
    imports, and block_until_ready is banned across every observability
    module. The scan is shared with the standalone lint entry
    (``python -m redcliff_tpu.obs.schema --check``)."""
    assert schema.check_sources() == []
    # and the registry the checker enforces is really closed over the new
    # modules: their module paths are under the discipline lists
    assert any(m.endswith("memory.py") for m in schema.LAZY_JAX_MODULES)
    assert any(m.endswith("profiling.py") for m in schema.LAZY_JAX_MODULES)
    assert any(m.endswith("trace_export.py") for m in schema.NO_JAX_MODULES)


def _iter_repo_sources():
    import redcliff_tpu

    pkg_root = os.path.dirname(os.path.abspath(redcliff_tpu.__file__))
    for dirpath, _dirs, files in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def test_event_and_span_name_literals_are_registered():
    """Static tripwire (ISSUE 8 satellite): every event/span name LITERAL
    in redcliff_tpu/ must be registered in the closed schema registry —
    an emitter added without registration fails here, at the source level,
    before any runtime path even has to exercise it. Scanned shapes:

    * ``<logger>.log("<event>", ...)``            -> EVENTS u LEDGER_EVENTS
    * ``span("<name>", ...)`` / ``record_span``    -> schema.SPAN_NAMES
    * dict literals carrying ``"event": "<name>"`` (the stdlib writers:
      supervisor ledger lines, flight/watch/regress artifacts)
    """
    import ast

    events = set(schema.EVENTS) | set(schema.LEDGER_EVENTS)
    bad = []
    for path in _iter_repo_sources():
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                fname = (fn.id if isinstance(fn, ast.Name)
                         else fn.attr if isinstance(fn, ast.Attribute)
                         else None)
                if not (fname in ("span", "record_span", "log")
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                name = node.args[0].value
                if fname == "log":
                    if name not in events:
                        bad.append((path, node.lineno, "event", name))
                elif name not in schema.SPAN_NAMES:
                    bad.append((path, node.lineno, "span", name))
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant) and k.value == "event"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                            and v.value not in events):
                        bad.append((path, node.lineno, "event", v.value))
    assert not bad, (
        "unregistered event/span name literals (register them in "
        f"redcliff_tpu/obs/schema.py and docs/ARCHITECTURE.md): {bad}")
    # the new ISSUE 8 + ISSUE 9 kinds are part of the closed registry
    assert {"cost_model", "watch", "regression",
            "memory", "profile"} <= set(schema.EVENTS)


# ---------------------------------------------------------------------------
# schema registry + validator
# ---------------------------------------------------------------------------
def test_schema_validator_accepts_known_rejects_drift():
    good = {"event": "compile", "wall_time": 1.0, "seq": 1, "pid": 2,
            "host": "h", "epoch": 0, "programs": 2, "compile_ms": 10.0,
            "cache_hits": 1, "cache_misses": 1, "grid_width": 8}
    assert schema.validate_record(good) == []
    unknown_event = {"event": "mystery", "wall_time": 1.0}
    assert any("unknown event" in e
               for e in schema.validate_record(unknown_event))
    missing = {"event": "compile", "wall_time": 1.0}
    errs = schema.validate_record(missing)
    assert any("missing required field 'epoch'" in e for e in errs)
    drift = dict(good, new_field=1)
    assert any("unregistered field 'new_field'" in e
               for e in schema.validate_record(drift))
    # dynamic GC-tracker families are admitted by pattern, typos are not
    ep = {"event": "epoch", "wall_time": 1.0, "epoch": 0,
          "f1_t0.0_factor0": 0.5, "deltacon0_factor1": 0.1,
          "forecasting_loss": 1.0}
    assert schema.validate_record(ep) == []
    assert schema.validate_record(dict(ep, f1x_typo=1))


def test_schema_validator_ledger_kind():
    att = {"event": "attempt", "attempt": 0, "cmd": ["x"], "rc": 0,
           "classification": "clean", "action": "stop", "backoff_s": 0.0,
           "started_at": 1.0, "duration_s": 2.0}
    assert schema.validate_record(att, kind="ledger") == []
    assert schema.validate_record({"event": "attempt"}, kind="ledger")
    fin = {"event": "final", "classification": "clean", "rc": 0,
           "attempts": 1}
    assert schema.validate_record(fin, kind="ledger") == []


def test_profiler_trace_noop_and_real(tmp_path):
    # disabled: no-op
    with profiler_trace(None):
        pass
    # enabled: produces a trace artifact tree
    out = tmp_path / "profile"
    with profiler_trace(str(out)):
        jax.block_until_ready(jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)))
    produced = [os.path.join(dp, f) for dp, _, fs in os.walk(out) for f in fs]
    assert produced, "profiler trace produced no files"
