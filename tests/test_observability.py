"""Structured observability (SURVEY §5): jsonl metric logging schema and the
opt-in profiler hook, replacing the reference's stdout-scrape observability
(ref README.md:96, redcliff_s_cmlp.py:1549-1569)."""
import json
import os

import jax
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import train_val_split
from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
from redcliff_tpu.train.trainer import TrainConfig, Trainer
from redcliff_tpu.utils.observability import (
    MetricLogger, jsonable, profiler_trace, read_jsonl)


def test_jsonable_coerces_numpy_and_dataclasses():
    cfg = TrainConfig(learning_rate=0.5)
    out = jsonable({
        "int": np.int64(3),
        "float": np.float32(1.5),
        "arr": np.arange(4).reshape(2, 2),
        "jax": jax.numpy.ones((2,)),
        "cfg": cfg,
        "nested": [np.float64(2.0), ("a", np.int32(1))],
    })
    assert out["int"] == 3 and isinstance(out["int"], int)
    assert out["float"] == 1.5 and isinstance(out["float"], float)
    assert out["arr"] == [[0, 1], [2, 3]]
    assert out["jax"] == [1.0, 1.0]
    assert out["cfg"]["learning_rate"] == 0.5
    assert out["nested"] == [2.0, ["a", 1]]
    json.dumps(out)  # round-trips through the encoder


def test_metric_logger_writes_and_reads(tmp_path):
    with MetricLogger(str(tmp_path)) as log:
        assert log.active
        log.log("epoch", epoch=0, loss=np.float32(1.25))
        log.log("epoch", epoch=1, loss=0.5)
        log.log("fit_end", best_it=1)
    recs = read_jsonl(str(tmp_path))
    assert [r["event"] for r in recs] == ["epoch", "epoch", "fit_end"]
    assert all("wall_time" in r for r in recs)
    assert recs[0]["loss"] == 1.25
    epochs = read_jsonl(str(tmp_path), event="epoch")
    assert len(epochs) == 2

    # resume appends rather than truncating
    with MetricLogger(str(tmp_path)) as log:
        log.log("fit_start", resume_epoch=2)
    assert len(read_jsonl(str(tmp_path))) == 4


def test_metric_logger_none_is_noop():
    log = MetricLogger(None)
    assert not log.active
    log.log("epoch", epoch=0)  # must not raise
    log.close()


def test_trainer_emits_epoch_schema(tmp_path):
    D = 4
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=1, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=3,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=4,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(0), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=64,
        recording_length=24, burnin_period=5, num_labeled_sys_states=1)
    train_ds, val_ds = train_val_split(np.asarray(X), np.asarray(Y),
                                       val_fraction=0.25,
                                       rng=np.random.default_rng(0))
    model = CMLPFM(CMLPFMConfig(num_chans=D, gen_lag=2, gen_hidden=(8,),
                                input_length=8))
    params = model.init(jax.random.PRNGKey(1))
    run = str(tmp_path / "run")
    trainer = Trainer(model, TrainConfig(learning_rate=1e-3, max_iter=3,
                                         batch_size=32, check_every=1))
    trainer.fit(params, train_ds, val_ds, true_GC=[graphs[0]], save_dir=run)

    recs = read_jsonl(run)
    events = [r["event"] for r in recs]
    assert events[0] == "fit_start"
    assert events[-1] == "fit_end"
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert len(epochs) == 3
    for i, r in enumerate(epochs):
        assert r["epoch"] == i
        assert isinstance(r["combo_loss"], float)
        assert isinstance(r["criteria"], float)
        # GC-vs-oracle metrics flattened in when a tracker is active
        assert "f1_t0.0_factor0" in r
        assert "roc_auc_t0.0_factor0" in r
        assert "deltacon0_factor0" in r
    start = recs[0]
    assert start["model"] == "CMLPFM"
    assert start["train_config"]["max_iter"] == 3
    end = recs[-1]
    assert set(end) >= {"best_it", "best_loss", "final_val_loss"}

    # the file is line-delimited JSON (the structured-logging contract)
    with open(os.path.join(run, "metrics.jsonl")) as f:
        for line in f:
            json.loads(line)


def test_profiler_trace_noop_and_real(tmp_path):
    # disabled: no-op
    with profiler_trace(None):
        pass
    # enabled: produces a trace artifact tree
    out = tmp_path / "profile"
    with profiler_trace(str(out)):
        jax.block_until_ready(jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8)))
    produced = [os.path.join(dp, f) for dp, _, fs in os.walk(out) for f in fs]
    assert produced, "profiler trace produced no files"
