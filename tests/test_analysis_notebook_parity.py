"""L6 parity: the analysis layer against the ICML notebook's own numbers.

The reference notebook
(/root/reference/evaluate/ICML2025_..._Notebook.ipynb) hard-codes its
experiment numbers directly in the analysis cells; these tests extract that
data from the notebook source and assert our analysis functions reproduce the
cells' arithmetic exactly:

* cell 83 — network complexity scores c = (nE / (nC^2 - nC))^-1 for the
  D4IC networks,
* the plotCrossExpSummaries banding (Low <= 7 < Moderate <= 13 < High),
* cells 34/35 — cross-fold factor-count selection means,
* cell 63 — ablation mean ± SEM (population std over per-factor F1 values).
"""
import ast
import json
import os
import pickle

import numpy as np
import pytest

from redcliff_tpu.eval.analysis import (
    complexity_category,
    factor_selection_table,
    network_complexity,
    parse_system_name,
    summarize_ablations,
)

NOTEBOOK = ("/root/reference/evaluate/"
            "ICML2025_REDCLIFF_S_CMLP_Experiments_and_Analyses_"
            "CodeRepo_Notebook.ipynb")


@pytest.fixture(scope="module")
def nb_cells():
    if not os.path.exists(NOTEBOOK):
        pytest.skip("reference notebook not available")
    with open(NOTEBOOK) as f:
        nb = json.load(f)
    return ["".join(c["source"]) for c in nb["cells"]]


def test_network_complexity_matches_notebook_cell83(nb_cells):
    """Cell 83 defines c = ((nE) / (nC^2 - nC))^-1 and applies it to the
    D4IC gold-standard networks (nC=10; nE in {15, 15, 12, 13, 16})."""
    src = nb_cells[83]
    assert "((x[1]) / (x[0]**2. - x[0]))**(-1)" in src
    for n_edges, expected in [(15, 90.0 / 15), (12, 90.0 / 12),
                              (13, 90.0 / 13), (16, 90.0 / 16)]:
        assert network_complexity(10, n_edges) == pytest.approx(expected)
    # the curated synthetic systems used in the banded summaries
    assert network_complexity(6, 2) == pytest.approx(15.0)
    assert network_complexity(12, 11) == pytest.approx(12.0)
    assert network_complexity(3, 1) == pytest.approx(6.0)


def test_complexity_banding_matches_plotcross_reference():
    """Band semantics of ref plotCrossExpSummaries_...py:64-65,144-149:
    Low <= 7 < Moderate <= 13 < High (boundaries inclusive on the left)."""
    assert complexity_category(network_complexity(3, 1)) == "Low"  # 6.0
    assert complexity_category(7.0) == "Low"
    assert complexity_category(7.0001) == "Moderate"
    assert complexity_category(network_complexity(12, 11)) == "Moderate"  # 12
    assert complexity_category(13.0) == "Moderate"
    assert complexity_category(network_complexity(6, 2)) == "High"  # 15.0
    d = parse_system_name(
        "numF2_numSF2_numN6_numE2_edgesNonlinear_labelsOneHot")
    assert (d["num_nodes"], d["num_edges"]) == (6, 2)


def _cell34_fold_values(src):
    """Parse the per-fold stopping-criteria sums of notebook cell 34:
    lines like `a = (v1 + v2 + ... + v5)/5.`"""
    out = {}
    for line in src.splitlines():
        line = line.strip()
        if "= (" in line and line.endswith(")/5."):
            name = line.split("=")[0].strip()
            inner = line[line.index("(") + 1 : line.rindex(")")]
            out[name] = [float(v) for v in inner.split("+")]
    return out


def test_factor_selection_means_match_notebook_cell34(nb_cells, tmp_path):
    """Cell 34 averages 5 folds' best stopping-criteria values per factor
    count (TST Full, nK in {3,4,5,6,9,18}).  factor_selection_table over
    run dirs whose metadata carries those best-criteria values must
    reproduce the notebook's printed means."""
    folds_by_var = _cell34_fold_values(nb_cells[34])
    assert set(folds_by_var) == {"a", "b", "c", "d", "e", "f"}
    nk_by_var = {"a": 3, "b": 4, "c": 5, "d": 6, "e": 9, "f": 18}
    run_dirs_by_nk = {}
    for var, vals in folds_by_var.items():
        nk = nk_by_var[var]
        dirs = []
        for fold, v in enumerate(vals):
            d = tmp_path / f"nK{nk}_fold{fold}"
            d.mkdir()
            with open(d / "training_meta_data_and_hyper_parameters.pkl",
                      "wb") as f:
                # history list whose min is the fold's best criteria value
                pickle.dump({"criteria_history": [v + 1.0, v, v + 0.5]}, f)
            dirs.append(str(d))
        run_dirs_by_nk[nk] = dirs
    table = factor_selection_table(run_dirs_by_nk,
                                   criteria_keys=("criteria_history",))
    for var, nk in nk_by_var.items():
        expected_mean = sum(folds_by_var[var]) / 5.0
        assert table[nk]["criteria_history_mean"] == pytest.approx(
            expected_mean, rel=1e-12), (var, nk)
        expected_sem = (np.std(folds_by_var[var]) / np.sqrt(5.0))
        assert table[nk]["criteria_history_sem"] == pytest.approx(
            expected_sem, rel=1e-12)


def _cell63_ablation_lists(src):
    """Extract each ablation block's REDCLIFF_S_CMLP value list from cell 63
    (`curr_results_by_alg = {...}` literals following each ablation print)."""
    blocks = {}
    current = None
    buf = None
    for line in src.splitlines():
        if "ablation:" in line.lower() and 'print("' in line:
            current = (line.split('"')[1].replace("\\n", "")
                       .strip().rstrip(":").strip())
        if line.strip().startswith("curr_results_by_alg = {"):
            buf = [line.split("=", 1)[1].strip()]
        elif buf is not None:
            buf.append(line)
        if buf is not None:
            joined = "\n".join(buf)
            if joined.count("{") == joined.count("}"):
                blocks[current] = ast.literal_eval(joined)
                buf = None
    return blocks


def test_ablation_summary_matches_notebook_cell63(nb_cells):
    """Cell 63 prints np.mean and np.std/sqrt(n) (population std) of the
    off-diag F1 values per ablation variant; summarize_ablations must use
    the same estimator (not sample std), and its full-model-minus-variant
    improvement must be the per-factor difference mean."""
    blocks = _cell63_ablation_lists(nb_cells[63])
    assert len(blocks) >= 3, list(blocks)
    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"

    def as_summary(vals):
        return {"cv": {paradigm: {"REDCLIFF_S_CMLP": {
            "f1_vals_across_factors": list(vals)}}}}

    # treat the first block (full model with CosSim) as the full model and
    # each other block as a variant
    names = list(blocks)
    summaries = {name: as_summary(blocks[name]["REDCLIFF_S_CMLP"])
                 for name in names}
    table = summarize_ablations(summaries, full_model_key=names[0])
    for name in names:
        vals = np.asarray(blocks[name]["REDCLIFF_S_CMLP"])
        assert table[name]["mean"] == pytest.approx(float(np.mean(vals)),
                                                    rel=1e-12)
        assert table[name]["sem"] == pytest.approx(
            float(np.std(vals) / np.sqrt(len(vals))), rel=1e-12)
    full_vals = np.asarray(blocks[names[0]]["REDCLIFF_S_CMLP"])
    var_vals = np.asarray(blocks[names[1]]["REDCLIFF_S_CMLP"])
    n = min(len(full_vals), len(var_vals))
    assert table[names[1]]["full_minus_variant_mean"] == pytest.approx(
        float(np.mean(full_vals[:n] - var_vals[:n])), rel=1e-12)
