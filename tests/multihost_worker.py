"""Worker process for the 2-process multi-host (DCN) grid test.

Each worker owns 2 virtual CPU devices; jax.distributed connects the workers
through the loopback coordinator, giving a 4-device global mesh spanning both
processes — the same topology as two TPU slices over DCN, scaled down. Run by
tests/test_multihost.py as:

    python tests/multihost_worker.py <port> <process_id> <num_processes> \
        <outdir> [local_devices]

The optional local_devices argument (default 2) sets this worker's virtual
device count, so the driver's dryrun can scale the same topology up
(e.g. 2 processes x 4 devices = an 8-device DCN-spanning mesh).
"""
import os
import pickle
import sys

PORT, PID, NPROC, OUTDIR = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                            sys.argv[4])
LOCAL_DEVICES = int(sys.argv[5]) if len(sys.argv) > 5 else 2

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# the session sitecustomize can register an experimental TPU backend that wins
# over JAX_PLATFORMS; hard-override exactly like tests/conftest.py
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402

from redcliff_tpu.data.datasets import ArrayDataset  # noqa: E402
from redcliff_tpu.models.redcliff import (  # noqa: E402
    RedcliffSCMLP, RedcliffSCMLPConfig)
from redcliff_tpu.parallel.distributed import (  # noqa: E402
    gather_to_host, initialize_distributed, is_distributed, process_local_slice,
    put_along_mesh)
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner  # noqa: E402
from redcliff_tpu.parallel.mesh import grid_mesh  # noqa: E402
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig  # noqa: E402


def main():
    assert initialize_distributed(f"127.0.0.1:{PORT}", NPROC, PID)
    assert jax.process_count() == NPROC, jax.process_count()
    assert jax.process_index() == PID
    assert len(jax.devices()) == NPROC * LOCAL_DEVICES  # global device list
    assert len(jax.local_devices()) == LOCAL_DEVICES
    assert is_distributed()

    # host-partitioned staging: this process feeds its contiguous block
    G = NPROC * LOCAL_DEVICES
    lo, hi = process_local_slice(G)
    assert hi - lo == G // NPROC

    mesh = grid_mesh()  # spans both processes
    assert mesh.devices.size == NPROC * LOCAL_DEVICES

    # sharded put: only the addressable shards materialize on this host
    probe = put_along_mesh(np.arange(G, dtype=np.float32), mesh)
    assert len(probe.addressable_shards) == LOCAL_DEVICES
    np.testing.assert_array_equal(gather_to_host(probe),
                                  np.arange(G, dtype=np.float32))

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01, factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    cfg = model.config
    rng = np.random.default_rng(0)  # same data on every host (replicated input)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(64, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(64, 3, 1)).astype(np.float32)
    ds = ArrayDataset(X, Y)

    spec = GridSpec(points=[{"gen_lr": 1e-3 * (i + 1)} for i in range(G)])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=32)
    runner = RedcliffGridRunner(model, tc, spec, mesh=mesh)
    res = runner.fit(jax.random.PRNGKey(0), ds, ds)

    assert res.val_history.shape == (2, G)
    assert np.all(np.isfinite(res.val_history))

    with open(os.path.join(OUTDIR, f"result_{PID}.pkl"), "wb") as f:
        pickle.dump({
            "val_history": res.val_history,
            "best_criteria": res.best_criteria,
            "best_leaf": np.asarray(jax.tree.leaves(res.best_params)[0]),
        }, f)
    print(f"worker {PID}: OK", flush=True)


if __name__ == "__main__":
    main()
