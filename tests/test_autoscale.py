"""SLO-driven fleet autoscaling tests (redcliff_tpu/fleet/autoscale, ISSUE
16).

Windowed-SLO units (trailing-window population filter, all-time
bit-identity), QoS-ladder units (rung knobs, apply_qos identity for clean
tenants vs deep-copy demotion, batch-key divergence so demoted work never
merges with undemoted siblings), queue-wait prediction and the submit-side
backpressure gate (inert unarmed, structured reject-with-ETA armed,
REDCLIFF_BACKPRESSURE opt-out), the control loop against an injected fake
worker pool (scale-up to cap, hysteresis cooldown, respawn/retire reaping,
state publication, QoS demote-at-cap/restore), and real-worker legs: an
autoscaled drain of a seeded submit storm (zero dead-letters, pool grows
then empties) and a demoted tenant completing with the QoS stamp in its
results manifest. The full breach->recovery storm soak is slow-marked.
"""
import json
import os
import time

import pytest

from redcliff_tpu.fleet import autoscale, chaos, history, planner
from redcliff_tpu.fleet.queue import BackpressureReject, FleetQueue
from redcliff_tpu.fleet.__main__ import TINY_SPEC
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs import slo as obs_slo
from redcliff_tpu.obs.logging import read_jsonl
from redcliff_tpu.runtime.supervisor import worker_exit_action
from redcliff_tpu.runtime.watchdog import EXIT_NUMERICS_ABORT

# every REDCLIFF_SLO_* unchecked: tick decisions in units below must not
# depend on thresholds leaking from the invoking environment
_NO_SLOS = {"queue_p99_s": None, "ttfa_p99_s": None,
            "deadline_hit_pct": None, "deadletter_pct": None}


def _tiny_spec(epochs=1):
    spec = json.loads(json.dumps(TINY_SPEC))
    spec["epochs"] = epochs
    return spec


def _clean_env(monkeypatch):
    for name in ("REDCLIFF_FAULT_INJECT", "REDCLIFF_FAULT_MARKER",
                 "REDCLIFF_SLO_QUEUE_P99_S", "REDCLIFF_SLO_TTFA_P99_S",
                 "REDCLIFF_SLO_DEADLINE_PCT", "REDCLIFF_SLO_DEADLETTER_PCT",
                 "REDCLIFF_BACKPRESSURE", "REDCLIFF_COST_MODEL_DIR",
                 "REDCLIFF_COMPILE_CACHE"):
        monkeypatch.delenv(name, raising=False)


# ---------------------------------------------------------------------------
# windowed SLO view (obs/slo.py window_s)
# ---------------------------------------------------------------------------
def _lifecycle(rid, tenant, t_submit, t_claim=None, t_attempt=None,
               t_settle=None, state="done"):
    recs = [{"event": "fleet_lifecycle", "kind": "submitted",
             "request_id": rid, "tenant": tenant, "wall_time": t_submit,
             "submitted_at": t_submit, "seq": 0}]
    if t_claim is not None:
        recs.append({"event": "fleet_lifecycle", "kind": "claimed",
                     "request_id": rid, "wall_time": t_claim, "seq": 1})
    if t_attempt is not None:
        recs.append({"event": "fleet_lifecycle", "kind": "attempt",
                     "request_id": rid, "wall_time": t_attempt,
                     "started_at": t_attempt, "seq": 2})
    if t_settle is not None:
        recs.append({"event": "fleet_lifecycle", "kind": "settled",
                     "request_id": rid, "wall_time": t_settle,
                     "state": state, "seq": 3})
    return recs


def test_windowed_slo_restricts_population_to_recent_requests():
    old = _lifecycle("req-old", "a", 0.0, t_claim=5.0, t_attempt=6.0,
                     t_settle=10.0)
    new = _lifecycle("req-new", "a", 1000.0, t_claim=1001.0,
                     t_attempt=1002.0, t_settle=1005.0)
    records = old + new

    full = obs_slo.compute_slo(records)
    assert full["requests"] == 2
    assert full["overall"]["queue_wait_s"]["p99"] == 5.0  # the old wait

    win = obs_slo.compute_slo(records, window_s=100.0)
    assert win["requests"] == 1  # req-old's last activity is at wall 10
    assert win["overall"]["queue_wait_s"]["p99"] == 1.0
    assert win["window"]["window_s"] == 100.0
    assert win["window"]["cutoff_wall"] == 1005.0 - 100.0
    # a breach absorbed long ago cannot keep the pool inflated
    thr = {"queue_p99_s": 2.0}
    assert obs_slo.compute_slo(records, thresholds=thr)["breaches"]
    assert obs_slo.compute_slo(records, thresholds=thr,
                               window_s=100.0)["breaches"] == []


def test_all_time_slo_bit_identical_without_window():
    records = (_lifecycle("r1", "a", 0.0, t_claim=2.0, t_settle=3.0)
               + _lifecycle("r2", "b", 1.0, t_claim=5.0))
    full = obs_slo.compute_slo(records)
    # the all-time view never grows window keys (the pre-windowing shape)
    assert set(full["window"]) == {"first_wall", "last_wall"}
    # a window covering everything computes the identical view
    win = obs_slo.compute_slo(records, window_s=1e9)
    win["window"].pop("window_s")
    win["window"].pop("cutoff_wall")
    assert win == full


# ---------------------------------------------------------------------------
# the QoS ladder
# ---------------------------------------------------------------------------
def test_qos_knobs_ladder_rungs_and_clamp():
    assert autoscale.qos_knobs(0) == {"rung": 0}
    assert autoscale.qos_knobs(1) == {"rung": 1, "precision_mode": "mixed"}
    r2 = autoscale.qos_knobs(2)
    assert r2["precision_mode"] == "mixed"
    assert r2["check_every_factor"] == autoscale.QOS_CHECK_EVERY_FACTOR
    assert autoscale.qos_knobs(99)["rung"] == autoscale.QOS_MAX_RUNG
    assert autoscale.qos_knobs(-3) == {"rung": 0}


def test_set_qos_active_qos_roundtrip(tmp_path):
    root = str(tmp_path)
    assert autoscale.active_qos(root) == {}
    rec = autoscale.set_qos(root, "hot", 2, reason="test", now=123.0)
    assert rec["rung"] == 2 and rec["set_at"] == 123.0
    active = autoscale.active_qos(root)
    assert set(active) == {"hot"}
    assert active["hot"]["precision_mode"] == "mixed"
    # clearing (rung 0) removes the durable rung file
    assert autoscale.set_qos(root, "hot", 0) is None
    assert autoscale.active_qos(root) == {}


def test_apply_qos_identity_for_clean_tenant_mutation_for_demoted(tmp_path):
    root = str(tmp_path)
    req = {"request_id": "r", "tenant": "hot",
           "spec": {"train_config": {"check_every": 2, "seed": 0}}}
    # no rung anywhere: the SAME object comes back (bit-identity guarantee)
    assert autoscale.apply_qos(req, {}) is req
    assert autoscale.apply_qos(req, autoscale.active_qos(root)) is req

    autoscale.set_qos(root, "hot", 2, reason="breach")
    rungs = autoscale.active_qos(root)
    out = autoscale.apply_qos(req, rungs)
    assert out is not req
    tc = out["spec"]["train_config"]
    assert tc["precision_mode"] == "mixed"
    assert tc["check_every"] == 2 * autoscale.QOS_CHECK_EVERY_FACTOR
    assert out["qos"]["rung"] == 2 and out["qos"]["reason"] == "breach"
    # the original record is untouched (deep copy, not mutation)
    assert "precision_mode" not in req["spec"]["train_config"]
    # a co-tenant's record still passes through unchanged
    other = {"request_id": "o", "tenant": "cool", "spec": {}}
    assert autoscale.apply_qos(other, rungs) is other


def test_demoted_spec_never_merges_with_undemoted_sibling(tmp_path):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    spec = _tiny_spec()
    q.submit("hot", [{"gen_lr": 1e-3}], spec=spec)
    q.submit("cool", [{"gen_lr": 2e-3}], spec=spec)
    pending = q.pending()
    assert len({planner.batch_key(r) for r in pending}) == 1
    assert len(planner.plan(pending, n_devices=1)["batches"]) == 1

    autoscale.set_qos(str(root), "hot", 1)
    rungs = autoscale.active_qos(str(root))
    demoted = [autoscale.apply_qos(r, rungs) for r in pending]
    # the demoted spec changes batch_key: two batches now, and the clean
    # tenant's record (and therefore its batch) is the identical object
    assert len({planner.batch_key(r) for r in demoted}) == 2
    assert len(planner.plan(demoted, n_devices=1)["batches"]) == 2
    cool = next(r for r in pending if r["tenant"] == "cool")
    assert any(r is cool for r in demoted)


# ---------------------------------------------------------------------------
# drain / queue-wait prediction + the submit-side backpressure gate
# ---------------------------------------------------------------------------
def test_predicted_drain_empty_then_unpriced_backlog(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    empty = autoscale.predicted_drain(q, default_eta_s=10.0)
    assert empty == {"pending": 0, "batches": 0, "priced": 0,
                     "unpriced": 0, "total_eta_s": 0.0,
                     "packing_width": 1}
    chaos.submit_storm(root, 2, tenant="t", seed=3, spec=_tiny_spec())
    drain = autoscale.predicted_drain(q, cost_model=None,
                                      default_eta_s=10.0)
    # distinct data seeds -> two batches, both unpriced at the default ETA
    assert drain["pending"] == 2 and drain["batches"] == 2
    assert drain["unpriced"] == 2 and drain["priced"] == 0
    assert drain["total_eta_s"] == 20.0 and drain["packing_width"] == 1


def test_predicted_drain_is_slot_aware(tmp_path, monkeypatch):
    """ISSUE 18 satellite: a packed worker's published slot occupancy
    divides the serial drain estimate, so the autoscaler stops
    over-spawning workers once packing lands; a STALE publication falls
    back to the serial estimate."""
    from redcliff_tpu.parallel import packing

    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    chaos.submit_storm(root, 2, tenant="t", seed=3, spec=_tiny_spec())
    packing.publish_state(root, {"pool": 4, "busy_devices": 4},
                          concurrent_batches=2)
    drain = autoscale.predicted_drain(q, cost_model=None,
                                      default_eta_s=10.0)
    assert drain["packing_width"] == 2
    assert drain["total_eta_s"] == 10.0  # 20s serial / 2 concurrent slots
    # stale publication (dead packed worker): serial estimate again
    packing.publish_state(root, {"pool": 4}, concurrent_batches=2,
                          now=time.time() - 10 * packing.STATE_FRESH_S)
    stale = autoscale.predicted_drain(q, cost_model=None,
                                      default_eta_s=10.0)
    assert stale["packing_width"] == 1 and stale["total_eta_s"] == 20.0


def test_predict_queue_wait_uses_fresh_published_worker_count(
        tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    monkeypatch.setenv(autoscale.ENV_DEFAULT_ETA_S, "10")
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    chaos.submit_storm(root, 2, tenant="t", seed=3, spec=_tiny_spec())
    pred = autoscale.predict_queue_wait_s(str(root), q=q, cost_model=None)
    assert pred["workers"] == 1 and pred["workers_source"] == "default"
    base_eta = pred["eta_s"]
    assert base_eta > 0 and pred["queue_depth"] == 2

    # a fresh autoscale.json divides the serial drain by the live pool
    autoscale._write_json_atomic(
        os.path.join(str(root), autoscale.STATE_NAME),
        {"wall_time": time.time(), "workers": 4, "n_devices": 1})
    pred4 = autoscale.predict_queue_wait_s(str(root), q=q, cost_model=None)
    assert pred4["workers"] == 4 and pred4["workers_source"] == "autoscaler"
    assert pred4["eta_s"] == pytest.approx(base_eta / 4.0, rel=1e-6)

    # a stale state file is distrusted: back to the conservative floor
    autoscale._write_json_atomic(
        os.path.join(str(root), autoscale.STATE_NAME),
        {"wall_time": time.time() - 10 * autoscale.STATE_FRESH_S,
         "workers": 4, "n_devices": 1})
    stale = autoscale.predict_queue_wait_s(str(root), q=q, cost_model=None)
    assert stale["workers_source"] == "default"


def test_backpressure_gate_inert_reject_and_opt_out(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    # unarmed (no queue-wait SLO): the gate must be invisible
    chaos.submit_storm(root, 2, tenant="t", seed=5, spec=_tiny_spec())
    assert len(q.pending()) == 2

    # armed with an unmeetable threshold: structured reject-with-ETA
    monkeypatch.setenv(obs_slo.ENV_QUEUE_P99_S, "0.01")
    monkeypatch.setenv(autoscale.ENV_DEFAULT_ETA_S, "30")
    with pytest.raises(BackpressureReject) as err:
        q.submit("t", [{"gen_lr": 1e-3}], spec=_tiny_spec())
    rej = err.value
    assert rej.tenant == "t" and rej.threshold_s == 0.01
    assert rej.eta_s > rej.threshold_s and rej.queue_depth == 2
    assert "backpressure" in str(rej) and "REDCLIFF_BACKPRESSURE" in str(rej)
    assert len(q.pending()) == 2  # nothing spooled
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    bp = [r for r in recs if r.get("event") == "backpressure"]
    assert bp and bp[-1]["kind"] == "reject" and bp[-1]["tenant"] == "t"

    # submit_storm counts rejections instead of raising
    storm = chaos.submit_storm(root, 2, tenant="t", seed=6,
                               spec=_tiny_spec())
    assert storm["submitted"] == [] and len(storm["rejected"]) == 2

    # the documented opt-out knob restores unconditional admission
    monkeypatch.setenv(autoscale.ENV_BACKPRESSURE, "0")
    q.submit("t", [{"gen_lr": 1e-3}], spec=_tiny_spec())
    assert len(q.pending()) == 3


# ---------------------------------------------------------------------------
# the control loop (injected fake worker pool — no subprocesses)
# ---------------------------------------------------------------------------
class FakeProc:
    def __init__(self, cmd=None):
        self.cmd = cmd
        self.rc = None

    def poll(self):
        return self.rc


def _scaler(root, procs, monkeypatch=None, thresholds=None, **policy_kw):
    kw = dict(max_workers=3, min_workers=0, target_drain_s=1.0,
              hysteresis_s=10.0, window_s=600.0, default_eta_s=30.0)
    kw.update(policy_kw)

    def spawn(cmd):
        procs.append(FakeProc(cmd))
        return procs[-1]

    return autoscale.Autoscaler(
        str(root), autoscale.AutoscalePolicy(**kw), spawn=spawn,
        thresholds=dict(_NO_SLOS, **(thresholds or {})))


def test_tick_scales_up_to_cap_and_publishes_state(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    chaos.submit_storm(root, 4, tenant="a", seed=1, spec=_tiny_spec())
    procs = []
    scaler = _scaler(root, procs)
    t0 = time.time()
    d = scaler.tick(now=t0)
    # 4 unpriced batches x 30s over a 1s drain target: capped at the max
    assert d["kind"] == "scale_up" and d["workers"] == 3
    assert len(d["spawned"]) == 3 and len(procs) == 3
    # the spawned argv is the drain-mode worker CLI (passive scale-down)
    assert "--drain" in procs[0].cmd and "work" in procs[0].cmd
    st = autoscale.load_state(str(root))
    assert st["workers"] == 3 and st["pending"] == 4
    assert st["target"] == 3 and st["max_workers"] == 3
    assert len(st["worker_ids"]) == 3

    # steady second tick: target == live, no pool change, still published
    d2 = scaler.tick(now=t0 + 0.1)
    assert d2["kind"] == "hold" and d2["reason"] == "steady"
    assert len(procs) == 3
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    kinds = [r["kind"] for r in recs if r.get("event") == "autoscale"]
    assert kinds.count("scale_up") == 1
    # pool changes land in the durable lifecycle ledger too (obs trace)
    hist = history.read_history(str(root))
    assert any(h.get("kind") == "autoscale" for h in hist)
    scaler.close()


def test_tick_hysteresis_gates_breach_driven_scale_up(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    storm = chaos.submit_storm(root, 2, tenant="hot", seed=2,
                               spec=_tiny_spec())
    # synthesize an observed queue-wait breach: a claim 50s after submit
    history.append_event(str(root), "claimed",
                         request_id=storm["submitted"][0], tenant="hot",
                         now=time.time() + 50.0)
    procs = []
    scaler = _scaler(root, procs, thresholds={"queue_p99_s": 0.05},
                     max_workers=4, target_drain_s=1000.0)
    t0 = time.time() + 60.0
    d = scaler.tick(now=t0)
    # eta/target rounds to 1; the standing breach nudges to live+1 = 1
    assert d["kind"] == "scale_up" and d["workers"] == 1
    assert d["breaches"] >= 1 and "breach" in d["reason"]
    assert scaler.first_breach_wall == t0

    # inside the cooldown the breach still wants live+1: held, not spawned
    d2 = scaler.tick(now=t0 + 1.0)
    assert d2["kind"] == "hold" and d2["reason"] == "hysteresis cooldown"
    assert len(procs) == 1
    # cooled: the breach-driven escalation proceeds
    d3 = scaler.tick(now=t0 + 11.0)
    assert d3["kind"] == "scale_up" and d3["workers"] == 2
    scaler.close()


def test_reap_respawns_crashes_and_retires_drains(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    procs = []
    scaler = _scaler(root, procs, max_workers=4)
    scaler.max_restarts = 1
    logger = scaler._ensure_logger()
    w1 = scaler._spawn_worker()
    w2 = scaler._spawn_worker()

    # restartable crash with budget left: respawned, restarts incremented
    procs[0].rc = 137
    scaler._reap(logger, time.time(), pending=True)
    assert w1 not in scaler.workers and len(scaler.workers) == 2
    crashed = next(wid for wid in scaler.workers if wid != w2)
    assert scaler.workers[crashed]["restarts"] == 1

    # the respawn crashes again: budget spent -> scale_down, not respawn
    scaler.workers[crashed]["proc"].rc = 137
    scaler._reap(logger, time.time(), pending=True)
    assert len(scaler.workers) == 1

    # clean drain retires (the passive scale-down) even with budget left
    scaler.workers[w2]["proc"].rc = 0
    scaler._reap(logger, time.time(), pending=False)
    assert scaler.workers == {}
    recs = [r for r in read_jsonl(str(root))
            if r.get("event") == "autoscale"]
    kinds = [r["kind"] for r in recs]
    assert kinds.count("respawn") == 1 and kinds.count("scale_down") == 2
    drained = [r for r in recs if r.get("classification") == "drained"]
    assert drained and drained[0]["worker"] == w2
    scaler.close()


def test_worker_exit_action_taxonomy():
    assert worker_exit_action(0, 0) == ("drained", "retire")
    assert worker_exit_action(137, 0, max_restarts=2) == ("crash", "respawn")
    assert worker_exit_action(137, 2, max_restarts=2) == ("crash", "stop")
    # terminal classes never respawn regardless of budget
    cls, action = worker_exit_action(EXIT_NUMERICS_ABORT, 0, max_restarts=9)
    assert cls == "numerics_abort" and action == "stop"
    assert worker_exit_action(-9, 0, max_restarts=2) \
        == ("signal:SIGKILL", "respawn")


def test_qos_demotes_at_cap_and_restores_when_clean(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    storm = chaos.submit_storm(root, 2, tenant="hot", seed=7,
                               spec=_tiny_spec())
    history.append_event(str(root), "claimed",
                         request_id=storm["submitted"][0], tenant="hot",
                         now=time.time() + 50.0)
    procs = []
    scaler = _scaler(root, procs, thresholds={"queue_p99_s": 0.05},
                     max_workers=1, hysteresis_s=0.0)
    t0 = time.time() + 60.0
    scaler.tick(now=t0)  # live 0 < cap: scaling is tried first, no demote
    assert autoscale.active_qos(str(root)) == {}
    scaler.tick(now=t0 + 1.0)  # at cap + breached: one rung per tick
    assert autoscale.active_qos(str(root))["hot"]["rung"] == 1
    scaler.tick(now=t0 + 2.0)
    assert autoscale.active_qos(str(root))["hot"]["rung"] == 2
    scaler.tick(now=t0 + 3.0)  # the ladder tops out
    assert autoscale.active_qos(str(root))["hot"]["rung"] == 2
    st = autoscale.load_state(str(root))
    assert st["qos"] == {"hot": 2}

    # window clean again: the rung is restored, the file removed
    scaler.thresholds = dict(_NO_SLOS)
    scaler.tick(now=t0 + 4.0)
    assert autoscale.active_qos(str(root)) == {}
    recs = [r for r in read_jsonl(str(root)) if r.get("event") == "qos"]
    assert [r["kind"] for r in recs] == ["demote", "demote", "restore"]
    assert recs[0]["precision_mode"] == "mixed"
    assert obs_schema.validate_records(read_jsonl(str(root))) == []
    # rung changes are in the lifecycle ledger (obs trace --fleet)
    assert any(h.get("kind") == "qos"
               for h in history.read_history(str(root)))
    scaler.close()


# ---------------------------------------------------------------------------
# real workers: autoscaled drain + the QoS manifest stamp
# ---------------------------------------------------------------------------
def test_autoscaler_drains_storm_with_real_workers(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    storm = chaos.submit_storm(root, 3, tenant="storm", seed=0,
                               spec=_tiny_spec())
    assert len(storm["submitted"]) == 3
    policy = autoscale.AutoscalePolicy(
        max_workers=2, min_workers=0, target_drain_s=1.0,
        hysteresis_s=0.5, window_s=600.0, default_eta_s=30.0)
    scaler = autoscale.Autoscaler(
        str(root), policy, lease_s=60.0, poll_s=0.5, max_attempts=2,
        max_restarts=1,
        worker_args=["--max-restarts", "1", "--base-delay-s", "0.05",
                     "--max-delay-s", "0.05"],
        thresholds=dict(_NO_SLOS, queue_p99_s=0.05))
    summary = scaler.run(interval_s=0.5, drain=True)
    st = FleetQueue(root).status()
    assert st["counts"]["done"] == 3
    assert st["counts"]["failed"] == 0 and st["counts"]["deadletter"] == 0
    # the pool grew past one worker, then emptied via self-drain retires
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    events = [r for r in recs if r.get("event") == "autoscale"]
    kinds = {r["kind"] for r in events}
    assert {"start", "scale_up", "scale_down", "stop"} <= kinds
    assert max(r.get("workers") or 0 for r in events) == 2
    state = autoscale.load_state(str(root))
    assert state["workers"] == 0 and state["pending"] == 0
    assert summary["workers"] == 0 and summary["first_breach_wall"]

    # fleet status / obs watch surface the autoscale section, schema-valid
    from redcliff_tpu.obs.watch import build_snapshot

    snap = build_snapshot(str(root))
    assert obs_schema.validate_record(snap) == []
    auto = snap["fleet"]["autoscale"]
    assert auto["workers"] == 0
    assert auto["last_decision"]["kind"] in ("hold", "scale_up")


def test_demoted_tenant_completes_with_qos_in_results(tmp_path, monkeypatch):
    _clean_env(monkeypatch)
    from redcliff_tpu.fleet.worker import work
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    root = tmp_path / "fleet"
    q = FleetQueue(root)
    autoscale.set_qos(str(root), "degraded", 2, reason="test demotion")
    rid = q.submit("degraded", [{"gen_lr": 1e-3}], spec=_tiny_spec())
    policy = SupervisorPolicy(
        max_restarts=2,
        backoff=RetryPolicy(max_attempts=100, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    n = work(str(root), drain=True, poll_s=0.2, lease_s=20.0,
             supervisor_policy=policy, env=env)
    assert n == 1
    res = q.result(rid)["result"]
    # the durable evidence: the fit ran at the demoted settings and the
    # results manifest says so
    assert res["qos"]["rung"] == 2
    assert res["qos"]["precision_mode"] == "mixed"
    assert res["qos"]["check_every"] == autoscale.QOS_CHECK_EVERY_FACTOR
    assert len(res["best_criteria"]) == 1


@pytest.mark.slow
def test_storm_breach_to_recovery_acceptance(tmp_path, monkeypatch):
    """The ISSUE 16 chaos acceptance: a seeded submit storm that breaches
    queue-wait p99 at a fixed 1-worker pool settles — SLOs restored going
    forward, zero dead-letters — once the autoscaler (+ armed
    backpressure) manages the pool, with every decision traceable."""
    _clean_env(monkeypatch)
    root = tmp_path / "fleet"
    storm = chaos.submit_storm(root, 6, tenant="storm", seed=0,
                               spec=_tiny_spec())
    assert len(storm["submitted"]) == 6
    # the storm's predicted serial drain breaches the armed queue-wait SLO
    pred = autoscale.predict_queue_wait_s(str(root), cost_model=None)
    assert pred["eta_s"] > 5.0

    policy = autoscale.AutoscalePolicy(
        max_workers=3, min_workers=0, target_drain_s=1.0,
        hysteresis_s=0.5, window_s=600.0, default_eta_s=30.0)
    scaler = autoscale.Autoscaler(
        str(root), policy, lease_s=60.0, poll_s=0.5, max_attempts=2,
        max_restarts=1,
        worker_args=["--max-restarts", "1", "--base-delay-s", "0.05",
                     "--max-delay-s", "0.05"],
        thresholds=dict(_NO_SLOS, queue_p99_s=5.0))
    summary = scaler.run(interval_s=0.5, drain=True)
    st = FleetQueue(root).status()
    assert st["counts"]["done"] == 6
    assert st["counts"]["deadletter"] == 0 and st["counts"]["failed"] == 0
    assert summary["first_breach_wall"] is not None
    # recovery: the drained fleet's forward-looking wait is inside the SLO
    after = autoscale.predict_queue_wait_s(str(root), cost_model=None)
    assert after["eta_s"] == 0.0
    # decisions traceable end to end: metrics chain AND lifecycle ledger
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    kinds = {r["kind"] for r in recs if r.get("event") == "autoscale"}
    assert {"scale_up", "scale_down"} <= kinds
    hist = history.read_history(str(root))
    assert any(h.get("kind") == "autoscale" for h in hist)
