"""End-to-end pipeline integration: curate -> array-task train (2 algorithms)
-> cross-algorithm eval -> grid selection -> analysis report, composing the
layers exclusively through the filesystem contract (run-folder names,
final_best_model.bin, summary pickles) the way the reference's SLURM flow does
(SURVEY §3.1/§3.4 call stacks)."""
import json
import os
import pickle

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.eval.analysis import generate_analysis_report
from redcliff_tpu.eval.cross_alg import run_cross_algorithm_comparison
from redcliff_tpu.eval.grid_selection import (load_grid_summaries,
                                              select_best_models)
from redcliff_tpu.train.driver import set_up_and_run_experiments
from redcliff_tpu.utils.config import read_in_data_args


def _write_cmlp_args(path):
    with open(path, "w") as f:
        json.dump({
            "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "8",
            "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "3",
            "lookback": "2", "check_every": "1", "verbose": "0",
            "output_length": "1", "wavelet_level": "None",
            "gen_hidden": "[8]", "gen_lr": "0.005",
            "gen_lag_and_input_len": "3", "FORECAST_COEFF": "1.0",
            "ADJ_L1_REG_COEFF": "0.001", "DAGNESS_REG_COEFF": "0.0",
            "DAGNESS_LAG_COEFF": "0.0", "DAGNESS_NODE_COEFF": "0.0",
        }, f)


def _write_redcliff_args(path):
    with open(path, "w") as f:
        json.dump({
            "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "8",
            "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "3",
            "lookback": "2", "check_every": "1", "verbose": "0",
            "output_length": "1", "wavelet_level": "None",
            "gen_hidden": "[8]", "gen_lr": "0.005",
            "gen_lag_and_input_len": "3", "embed_lag": "4",
            "FORECAST_COEFF": "1.0", "ADJ_L1_REG_COEFF": "0.001",
            "num_factors": "2", "num_supervised_factors": "2",
            "use_sigmoid_restriction": "1",
            "factor_score_embedder_type": "Vanilla_Embedder",
            "primary_gc_est_mode": "fixed_factor_exclusive",
            "forward_pass_mode": "apply_factor_weights_at_each_sim_step",
            "FACTOR_SCORE_COEFF": "10.0", "DAGNESS_REG_COEFF": "0.0",
            "DAGNESS_LAG_COEFF": "0.0", "DAGNESS_NODE_COEFF": "0.0",
            "FACTOR_WEIGHT_L1_COEFF": "0.01", "FACTOR_COS_SIM_COEFF": "0.01",
            "training_mode": "combined", "embed_lr": "0.005",
            "embed_eps": "0.0001", "embed_weight_decay": "0.0",
            "num_pretrain_epochs": "0", "num_acclimation_epochs": "0",
            "prior_factors_path": "None", "cost_criteria": "combo",
            "unsupervised_start_index": "0",
            "max_factor_prior_batches": "5",
            "stopping_criteria_forecast_coeff": "1.0",
            "stopping_criteria_factor_coeff": "1.0",
            "stopping_criteria_cosSim_coeff": "1.0",
            "deltaConEps": "0.1", "in_degree_coeff": "1.0",
            "out_degree_coeff": "1.0",
        }, f)


@pytest.mark.slow
def test_full_pipeline_curate_train_eval_select_report(tmp_path):
    # --- 1. curation: shards + cached-args with stringified true graphs ---
    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path / "data"), fold_id=0, num_nodes=5, num_factors=2,
        num_supervised_factors=2, num_samples_in_train_set=16,
        num_samples_in_val_set=8, sample_recording_len=30,
        folder_name="toySys")
    data_args_file = os.path.join(fold_dir, "data_fold0_cached_args.txt")
    assert os.path.isfile(data_args_file)

    # the true graphs round-trip through the cached-args text contract
    gc_args = read_in_data_args(
        {"model_type": "REDCLIFF_S_CMLP",
         "data_cached_args_file": data_args_file},
        read_in_gc_factors_for_eval=True)
    true_gcs = gc_args["true_GC_factors"]
    assert len(true_gcs) == 2

    # --- 2. array-task training of two algorithm families, one root each ---
    roots = {}
    for model_type, writer, args_name in (
            ("REDCLIFF_S_CMLP", _write_redcliff_args,
             "REDCLIFF_S_CMLP_toy_cached_args.txt"),
            ("cMLP", _write_cmlp_args, "cMLP_toy_cached_args.txt")):
        margs = tmp_path / args_name
        writer(str(margs))
        # root folder names carry the algorithm name: the eval layer resolves
        # them by substring (cross_alg.select_algorithm_root)
        alias = "CMLP" if model_type == "cMLP" else model_type
        save_root = tmp_path / "runs" / f"{alias}_models"
        os.makedirs(save_root)
        task_id = set_up_and_run_experiments(
            {"save_root_path": str(save_root)}, [str(margs)],
            [data_args_file], possible_model_types=[model_type],
            possible_data_sets=["data_fold0"], task_id=1)
        assert task_id == 1
        runs = os.listdir(save_root)
        assert len(runs) == 1
        run_dir = save_root / runs[0]
        assert (run_dir / "final_best_model.bin").exists()
        assert (run_dir / "training_meta_data_and_hyper_parameters.pkl"
                ).exists()
        assert (run_dir / "metrics.jsonl").exists()  # observability contract
        roots[alias] = str(save_root)

    # --- 3. cross-algorithm evaluation through the filesystem contract ---
    sys_key = "numF2_numSF2_numN5_numE6_toy_data"
    eval_root = tmp_path / "evals"
    out_dir = eval_root / sys_key
    full = run_cross_algorithm_comparison(
        list(roots.values()), {"data_fold0": {0: true_gcs}}, str(out_dir),
        num_folds=1, plot=True)
    assert set(full["data_fold0"]["fold_0_details"]) == {
        "REDCLIFF_S_CMLP", "CMLP"}
    assert (out_dir / "full_comparrisson_summary.pkl").exists()
    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
    by_alg = full["data_fold0"][paradigm]
    for alg in ("REDCLIFF_S_CMLP", "CMLP"):
        f1s = by_alg[alg]["f1_vals_across_factors"]
        assert len(f1s) == 2 and all(np.isfinite(f1s))

    # --- 4. grid-search selection over the trained run metadata ---
    summaries = load_grid_summaries(roots["REDCLIFF_S_CMLP"])
    best = select_best_models(
        roots["REDCLIFF_S_CMLP"],
        selection_criteria=("forecasting_loss", "factor_loss"))
    assert best["forecasting_loss"]["best_run"] in summaries

    # --- 5. one-command analysis report over the eval tree ---
    report = generate_analysis_report(str(eval_root), str(tmp_path / "report"))
    assert sys_key in report["tables"]["off_diag_f1"]["mean"]
    assert report["system_details"][sys_key]["dataset_complexity"] == \
        pytest.approx((5 * 5 - 5) / 6)
    report_files = os.listdir(tmp_path / "report")
    assert "analysis_report.pkl" in report_files
    assert any(f.endswith(".csv") for f in report_files)
    # collected per-system figures from the cross-alg run landed in the report
    assert report["figures"]
