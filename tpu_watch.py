"""Opportunistic TPU bench watcher.

The axon TPU tunnel in this environment is intermittently reachable (observed
round 3: one ~30-minute live window in ~7 hours, every other probe hung).
bench.py already probes with killable subprocesses on a spread schedule, but a
single bench invocation can only sample a few minutes of tunnel availability —
if the driver's end-of-round bench run misses the window, no TPU number lands
in the round artifact even when the tunnel WAS alive earlier.

This watcher closes that gap: it runs in the background for the whole round,
probing the tunnel on a steady cadence, and the moment a probe succeeds it runs
the FULL bench measurement (`bench.py --measure tpu` — scan-dispatch G-curve
including G>=128 scanned MFU, vs_baseline sequential ratio) in a killable child
and writes the result to `experiments/TPU_BENCH_CACHE.json` with a
`measured_at` timestamp. `bench.py` then embeds the newest cached TPU
measurement (marked `cached: true`, with provenance) whenever its own live
probes fail, so the round's BENCH artifact carries real-TPU evidence from any
live window during the round, not just the minutes the driver happened to run.

Also validates the Pallas group-lasso prox kernel on the real chip during the
same window (cheap; one extra child) and records the max abs error in the
cache.

Usage: python tpu_watch.py [--duration-s 39600] [--interval-s 420]
Writes a human log to experiments/tpu_watch.log.
"""
import argparse
import datetime
import json
import os
import subprocess
import sys
import time

import bench  # reuse the killable probe/measure children + cache writer/lock
from redcliff_tpu.runtime import watchdog as rt_watchdog
from redcliff_tpu.runtime.retry import RetryPolicy, retry

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE_PATH = bench.TPU_CACHE_PATH
LOG_PATH = os.path.join(REPO, "experiments", "tpu_watch.log")

# after a successful measurement, wait this long before re-measuring on a later
# live window (a fresher timestamp is worth a re-run, but not back-to-back)
REFRESH_MIN_S = 90 * 60.0

PALLAS_CHECK_SRC = r"""
import json
import numpy as np
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu", "tunnel fell back to cpu"
from redcliff_tpu.ops.factor_mix import factor_mix_pallas, factor_mix_reference
from redcliff_tpu.ops.pallas_prox import gl_prox_pallas
from redcliff_tpu.ops.prox import prox_update
rng = np.random.default_rng(0)
W = jnp.asarray(rng.normal(size=(5, 12, 32, 12, 4)).astype(np.float32))
lam, lr = 0.013, 0.002
got = gl_prox_pallas(W, lam, lr, interpret=False)
want = prox_update(W, lam, lr, "GL")
err = float(jnp.max(jnp.abs(got - want)))
# fused factor-mix kernel (ISSUE 14), compiled on the real chip
fw = jnp.asarray(rng.random((64, 5)).astype(np.float32))
fp = jnp.asarray(rng.normal(size=(5, 64, 1, 10)).astype(np.float32))
fm_got = factor_mix_pallas(fw, fp, interpret=False)
fm_want = factor_mix_reference(fw, fp)
fm_err = float(jnp.max(jnp.abs(fm_got - fm_want)))
print(json.dumps({"ok": err < 5e-6 and fm_err < 5e-6, "max_abs_err": err,
                  "factor_mix_max_abs_err": fm_err,
                  "device": jax.devices()[0].device_kind}))
"""


def _utcnow():
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _log(msg):
    line = f"[{_utcnow()}] {msg}"
    print(line, flush=True)
    with open(LOG_PATH, "a") as f:
        f.write(line + "\n")


def _pallas_check(timeout_s=420.0):
    try:
        r = subprocess.run([sys.executable, "-c", PALLAS_CHECK_SRC],
                           capture_output=True, text=True, timeout=timeout_s,
                           cwd=REPO)
        for line in reversed(r.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
        return {"ok": False, "error": f"rc={r.returncode}: {r.stderr[-300:]}"}
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"pallas check hung > {timeout_s:.0f}s"}
    except Exception as e:  # noqa: BLE001 - cache must record, not crash
        return {"ok": False, "error": repr(e)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration-s", type=float, default=39600.0)
    ap.add_argument("--interval-s", type=float, default=420.0)
    args = ap.parse_args()

    t0 = time.monotonic()
    started_at = _utcnow()
    state = {"successes": 0, "last_success_mono": None}
    _log(f"tpu_watch start: duration={args.duration_s:.0f}s "
         f"interval={args.interval_s:.0f}s cache={CACHE_PATH}")

    # liveness, log-only: the probe/measure children carry their own kill
    # timeouts, but a tick wedged OUTSIDE them (cache lock, filesystem)
    # would silently end the watch — the watchdog heartbeat makes that a
    # logged incident instead of a mystery. hard_exit=False: the watcher is
    # opportunistic, killing it buys nothing
    tick_budget = max(3.0 * args.interval_s, 1800.0)
    rt_watchdog.REGISTRY.register("tpu_watch_tick", budget_s=tick_budget)
    wd = rt_watchdog.Watchdog(
        policy=rt_watchdog.WatchdogPolicy(poll_s=60.0, hard_exit=False,
                                          latch_preempt=False),
        on_hang=lambda rec: _log(f"WATCHDOG: tick wedged {rec['components']}"))

    def watch_tick(attempt):
        """One cadence tick: probe; on a live window, measure+cache.
        Returns a status string for the retry attempt log."""
        rt_watchdog.stamp("tpu_watch_tick")
        ok, info = bench._probe_accelerator()
        _log(f"probe {attempt + 1}: ok={ok} {info}")
        if not ok:
            return "no tunnel"
        last = state["last_success_mono"]
        fresh_enough = (last is not None
                        and time.monotonic() - last < REFRESH_MIN_S)
        if not fresh_enough:
            # survive watcher restarts: a cache written minutes ago by a
            # previous watcher/bench process is just as fresh
            cached = bench._load_tpu_cache()
            if cached is not None:
                # age_hours is computed by the loader; a backfilled seed
                # is always old enough to re-measure on a live window
                fresh_enough = cached["age_hours"] * 3600.0 < REFRESH_MIN_S
        if fresh_enough:
            _log("live window but cache is fresh; skipping re-measure")
            return "live; cache fresh"
        if not bench._acquire_measure_lock(wait_s=0.0):
            # a live bench.py run owns the chip; its result lands in the
            # same cache, so this window is covered either way
            _log("live window but another measurement holds the lock")
            return "live; lock held elsewhere"
        try:
            _log("tunnel LIVE -> running full TPU bench measurement")
            payload, minfo = bench._run_measure_child("tpu")
            if payload is not None and payload.get("value"):
                pallas = _pallas_check()
                bench._write_tpu_cache(
                    payload, source="tpu_watch.py opportunistic window",
                    extras={"watch_started_at": started_at,
                            "probe_attempts_before_success": attempt + 1,
                            "pallas_prox_check": pallas})
                state["successes"] += 1
                state["last_success_mono"] = time.monotonic()
                _log(f"MEASUREMENT CACHED: value={payload.get('value')} "
                     f"vs_baseline={payload.get('vs_baseline')} "
                     f"device={payload.get('device')} pallas={pallas}")
                return "measured"
            _log(f"measurement failed mid-window: {minfo}")
            return f"measure failed: {minfo}"
        finally:
            bench._release_measure_lock()

    # the watcher is a constant-cadence instance of the shared retry
    # primitive: multiplier 1.0 = steady interval, the deadline is the watch
    # duration, and is_success is never True because a measurement does NOT
    # end the watch (a later live window refreshes the cache again)
    policy = RetryPolicy(
        max_attempts=max(1, int(args.duration_s // args.interval_s) + 1),
        base_delay_s=args.interval_s, multiplier=1.0,
        max_delay_s=args.interval_s, jitter_frac=0.0,
        deadline_s=args.duration_s)
    with wd:
        outcome = retry(watch_tick, policy, is_success=lambda r: False,
                        info_of=lambda r: r)
    _log(f"tpu_watch done: {len(outcome.attempts)} probes, "
         f"{state['successes']} cached measurements")
    _log("retry outcome: " + json.dumps(outcome.log()))


if __name__ == "__main__":
    main()
